"""Gluon blocks/params/hybridize (ref: tests/python/unittest/test_gluon.py)."""
import numpy as onp
import pytest

import mxnet_tpu as mx
from mxnet_tpu import nd, autograd, gluon
from mxnet_tpu.gluon import nn
from mxnet_tpu.test_utils import assert_almost_equal


def test_dense_forward():
    net = nn.Dense(4, in_units=3)
    net.initialize()
    x = nd.ones((2, 3))
    out = net(x)
    assert out.shape == (2, 4)
    w = net.weight.data().asnumpy()
    b = net.bias.data().asnumpy()
    assert_almost_equal(out, onp.ones((2, 3)).dot(w.T) + b, rtol=1e-5)


def test_deferred_init():
    net = nn.Dense(4)
    net.initialize()
    x = nd.ones((2, 7))
    out = net(x)
    assert out.shape == (2, 4)
    assert net.weight.shape == (4, 7)


def test_sequential():
    net = nn.HybridSequential()
    net.add(nn.Dense(8, activation='relu'))
    net.add(nn.Dense(3))
    net.initialize()
    out = net(nd.ones((2, 5)))
    assert out.shape == (2, 3)
    assert len(net) == 2
    assert isinstance(net[0], nn.Dense)


def test_collect_params_naming():
    net = nn.HybridSequential(prefix='model_')
    with net.name_scope():
        net.add(nn.Dense(4))
        net.add(nn.Dense(2))
    params = net.collect_params()
    names = list(params.keys())
    assert all(n.startswith('model_') for n in names)
    assert len(names) == 4


def test_param_save_load(tmp_path):
    net = nn.Dense(3, in_units=2)
    net.initialize()
    fname = str(tmp_path / 'p.params')
    net.save_parameters(fname)
    net2 = nn.Dense(3, in_units=2)
    net2.load_parameters(fname)
    assert_almost_equal(net.weight.data(), net2.weight.data())


def test_conv_pool():
    net = nn.HybridSequential()
    net.add(nn.Conv2D(4, kernel_size=3, padding=1, activation='relu'))
    net.add(nn.MaxPool2D(2, 2))
    net.initialize()
    out = net(nd.ones((2, 3, 8, 8)))
    assert out.shape == (2, 4, 4, 4)


def test_batchnorm_train_inference():
    net = nn.BatchNorm(in_channels=3)
    net.initialize()
    x = nd.array(onp.random.randn(4, 3, 2, 2).astype(onp.float32))
    with autograd.record():
        out = net(x)
    xn = x.asnumpy()
    mean = xn.mean(axis=(0, 2, 3))
    var = xn.var(axis=(0, 2, 3))
    expect = (xn - mean[None, :, None, None]) / onp.sqrt(
        var[None, :, None, None] + 1e-5)
    assert_almost_equal(out, expect, rtol=1e-3, atol=1e-4)
    # running stats updated
    rm = net.running_mean.data().asnumpy()
    assert_almost_equal(rm, 0.1 * mean, rtol=1e-3, atol=1e-5)
    # inference uses running stats
    out2 = net(x)
    rv = net.running_var.data().asnumpy()
    expect2 = (xn - rm[None, :, None, None]) / onp.sqrt(
        rv[None, :, None, None] + 1e-5)
    assert_almost_equal(out2, expect2, rtol=1e-3, atol=1e-4)


def test_hybridize_matches_eager():
    net = nn.HybridSequential()
    net.add(nn.Dense(16, activation='relu'))
    net.add(nn.Dense(4))
    net.initialize()
    x = nd.array(onp.random.rand(5, 8).astype(onp.float32))
    eager = net(x).asnumpy()
    net.hybridize()
    hybrid = net(x).asnumpy()
    assert_almost_equal(eager, hybrid, rtol=1e-5)
    # grads through hybridized path
    for p in net.collect_params().values():
        pass
    x2 = nd.array(onp.random.rand(5, 8).astype(onp.float32))
    w = net[0].weight
    with autograd.record():
        loss = (net(x2) ** 2).sum()
    loss.backward()
    g_hybrid = w.grad().asnumpy().copy()
    net2 = nn.HybridSequential()
    net2.add(nn.Dense(16, activation='relu'))
    net2.add(nn.Dense(4))
    net2.initialize()
    for (n1, p1), (n2, p2) in zip(sorted(net.collect_params().items()),
                                  sorted(net2.collect_params().items())):
        p2.set_data(p1.data())
    with autograd.record():
        loss2 = (net2(x2) ** 2).sum()
    loss2.backward()
    assert_almost_equal(g_hybrid, net2[0].weight.grad(), rtol=1e-4, atol=1e-5)


def test_hybridize_batchnorm_stats_update():
    net = nn.HybridSequential()
    net.add(nn.Dense(4, in_units=3))
    net.add(nn.BatchNorm(in_channels=4))
    net.initialize()
    net.hybridize()
    x = nd.array(onp.random.rand(8, 3).astype(onp.float32))
    before = net[1].running_mean.data().asnumpy().copy()
    with autograd.record():
        net(x)
    after = net[1].running_mean.data().asnumpy()
    assert not onp.allclose(before, after)


def test_trainer_sgd_step():
    net = nn.Dense(1, in_units=2, use_bias=False)
    net.initialize()
    net.weight.set_data(nd.array([[1.0, 1.0]]))
    trainer = gluon.Trainer(net.collect_params(), 'sgd',
                            {'learning_rate': 0.1})
    x = nd.array([[1., 2.]])
    with autograd.record():
        loss = net(x).sum()
    loss.backward()
    trainer.step(1)
    # grad = [1, 2]; w = w - 0.1*grad
    assert_almost_equal(net.weight.data(), [[0.9, 0.8]], rtol=1e-6)


def test_embedding_layer():
    net = nn.Embedding(10, 4)
    net.initialize()
    out = net(nd.array([1, 3]))
    assert out.shape == (2, 4)


def test_losses():
    from mxnet_tpu.gluon import loss as gloss
    pred = nd.array([[1., 2., 3.], [3., 2., 1.]])
    label = nd.array([2, 0])
    l = gloss.SoftmaxCrossEntropyLoss()(pred, label)
    expect = -onp.log(onp.exp([3, 3]) / onp.exp([[1, 2, 3], [3, 2, 1]])
                      .sum(axis=1))
    assert_almost_equal(l, expect, rtol=1e-5)
    l2 = gloss.L2Loss()(nd.array([1., 2.]), nd.array([0., 0.]))
    assert_almost_equal(l2, [0.5, 2.0])
    l1 = gloss.L1Loss()(nd.array([1., -2.]), nd.array([0., 0.]))
    assert_almost_equal(l1, [1., 2.])


def test_lambda_blocks():
    net = nn.HybridLambda('tanh')
    out = net(nd.array([0.]))
    assert_almost_equal(out, [0.])
    net2 = nn.Lambda(lambda x: x * 2)
    assert_almost_equal(net2(nd.array([3.])), [6.])


def test_global_norm_clip():
    from mxnet_tpu.gluon.utils import clip_global_norm
    arrays = [nd.ones((2, 2)) * 3, nd.ones((3,)) * 4]
    norm = clip_global_norm(arrays, 1.0)
    total = onp.sqrt(sum((a.asnumpy() ** 2).sum() for a in arrays))
    assert abs(total - 1.0) < 1e-5


def test_block_repr_and_summary(capsys):
    net = nn.HybridSequential()
    net.add(nn.Dense(4, in_units=2))
    net.initialize()
    repr(net)
    net.summary(nd.ones((1, 2)))
    captured = capsys.readouterr()
    assert 'Total params' in captured.out


def _train_n_steps(optname, kw, fused, n=4, seed=11):
    import mxnet_tpu as mx
    from mxnet_tpu import gluon, autograd, nd
    from mxnet_tpu.gluon import nn
    mx.random.seed(seed)
    onp.random.seed(seed)
    net = nn.HybridSequential()
    net.add(nn.Dense(16, activation='relu'), nn.Dense(8))
    net.initialize(mx.init.Xavier())
    net(nd.ones((2, 12)))
    tr = gluon.Trainer(net.collect_params(), optname, dict(kw))
    if not fused:
        tr._fused_disabled = True
    X = onp.random.randn(32, 12).astype(onp.float32)
    y = onp.random.randint(0, 8, 32).astype(onp.int32)
    lossfn = gluon.loss.SoftmaxCrossEntropyLoss()
    for _ in range(n):
        with autograd.record():
            loss = lossfn(net(nd.array(X)), nd.array(y))
        loss.backward()
        tr.step(32)
    params = [v.data().asnumpy() for k, v in
              sorted(net.collect_params().items(),
                     key=lambda kv: kv[0].split('_', 1)[1])]
    return tr, params


def test_trainer_fused_update_matches_eager():
    """Trainer.step runs ONE compiled multi-tensor XLA update program per
    step (the analog of the reference's preloaded_multi_sgd fused ops,
    ref src/operator/contrib/preloaded_multi_sgd.cc) and matches the eager
    per-param loop bit-for-bit-ish across optimizers. Host-sync optimizers
    (LARS) must fall back transparently."""
    for optname, kw in [
            ('sgd', {'learning_rate': 0.05, 'momentum': 0.9, 'wd': 1e-4}),
            ('nag', {'learning_rate': 0.05, 'momentum': 0.9}),
            ('adam', {'learning_rate': 1e-2}),
            ('adamw', {'learning_rate': 1e-2}),
            ('lamb', {'learning_rate': 1e-2}),
            ('rmsprop', {'learning_rate': 1e-3}),
            ('adagrad', {'learning_rate': 1e-2}),
            ('ftml', {'learning_rate': 1e-2}),
            ('adadelta', {}),
            ('signum', {'learning_rate': 1e-2}),
            ('ftrl', {'learning_rate': 1e-2}),
            ('adamax', {'learning_rate': 1e-2}),
            ('dcasgd', {'learning_rate': 1e-2})]:
        tr_f, p_fused = _train_n_steps(optname, kw, fused=True)
        tr_e, p_eager = _train_n_steps(optname, kw, fused=False)
        err = max(onp.abs(a - b).max() for a, b in zip(p_fused, p_eager))
        assert not getattr(tr_f, '_fused_disabled', False), \
            f"{optname} fell back to the eager loop"
        assert err < 1e-5, (optname, err)
        # one compiled program, reused every step (no per-step retrace)
        jitted = tr_f._fused_cache[2]
        if hasattr(jitted, '_cache_size'):
            assert jitted._cache_size() == 1, jitted._cache_size()


def test_trainer_fused_impure_fallback():
    """Optimizers with impure update() — LARS (host norm sync), Nadam
    (python-state m_schedule) — must refuse the fused path and the eager
    fallback must produce identical results."""
    for optname, kw in [('lars', {'learning_rate': 0.05}),
                        ('nadam', {'learning_rate': 1e-2})]:
        tr_f, p_fused = _train_n_steps(optname, kw, fused=True)
        tr_e, p_eager = _train_n_steps(optname, kw, fused=False)
        assert getattr(tr_f, '_fused_disabled', False), optname
        err = max(onp.abs(a - b).max() for a, b in zip(p_fused, p_eager))
        assert err == 0.0, (optname, err)
