"""INT8 quantization tests (ref: tests/python/quantization/test_quantization.py)."""
import numpy as onp
import pytest

import mxnet_tpu as mx
from mxnet_tpu import nd, gluon
from mxnet_tpu.contrib.quantization import (
    quantize_net, QuantizedDense, QuantizedConv2D, _get_optimal_threshold)


def test_quantize_dequantize_roundtrip_int8():
    x = nd.array(onp.random.RandomState(0).uniform(-3, 3, (4, 16)).astype('float32'))
    q, lo, hi = nd.quantize_v2(x, out_type='int8')
    assert q.dtype == onp.int8
    back = nd.dequantize(q, lo, hi)
    assert onp.allclose(back.asnumpy(), x.asnumpy(), atol=3.0 / 127 + 1e-6)


def test_quantize_dequantize_roundtrip_uint8():
    x = nd.array(onp.random.RandomState(1).uniform(0, 5, (8, 8)).astype('float32'))
    q, lo, hi = nd.quantize(x, float(x.asnumpy().min()),
                            float(x.asnumpy().max()), out_type='uint8')
    assert q.dtype == onp.uint8
    back = nd.dequantize(q, lo, hi)
    assert onp.allclose(back.asnumpy(), x.asnumpy(), atol=5.0 / 255 + 1e-6)


def test_quantize_calibrated_range_clips():
    x = nd.array(onp.array([[-10.0, 0.5, 10.0]], dtype='float32'))
    q, lo, hi = nd.quantize_v2(x, out_type='int8', min_calib_range=-1.0,
                               max_calib_range=1.0)
    qn = q.asnumpy()
    assert qn[0, 0] == -127 and qn[0, 2] == 127


def test_quantized_fully_connected_matches_float():
    rs = onp.random.RandomState(2)
    x = rs.uniform(-1, 1, (5, 32)).astype('float32')
    w = rs.uniform(-1, 1, (8, 32)).astype('float32')
    qx, xlo, xhi = nd.quantize_v2(nd.array(x), out_type='int8')
    qw, wlo, whi = nd.quantize_v2(nd.array(w), out_type='int8')
    out32, olo, ohi = nd.quantized_fully_connected(
        qx, qw, None, xlo, xhi, wlo, whi, num_hidden=8, no_bias=True)
    out = nd.dequantize(out32, olo, ohi).asnumpy()
    ref = x @ w.T
    assert onp.abs(out - ref).max() < 0.15


def test_quantized_conv_matches_float():
    rs = onp.random.RandomState(3)
    x = rs.uniform(-1, 1, (2, 3, 8, 8)).astype('float32')
    w = rs.uniform(-1, 1, (4, 3, 3, 3)).astype('float32')
    qx, xlo, xhi = nd.quantize_v2(nd.array(x), out_type='int8')
    qw, wlo, whi = nd.quantize_v2(nd.array(w), out_type='int8')
    out32, olo, ohi = nd.quantized_conv(
        qx, qw, None, xlo, xhi, wlo, whi, kernel=(3, 3), stride=(1, 1),
        pad=(1, 1), num_filter=4, no_bias=True)
    out = nd.dequantize(out32, olo, ohi).asnumpy()
    ref = nd.convolution(nd.array(x), nd.array(w), kernel=(3, 3),
                         stride=(1, 1), pad=(1, 1), num_filter=4,
                         no_bias=True).asnumpy()
    assert onp.abs(out - ref).max() < 0.3


def test_quantized_pooling_int8_domain():
    rs = onp.random.RandomState(4)
    x = rs.uniform(-1, 1, (1, 2, 4, 4)).astype('float32')
    qx, lo, hi = nd.quantize_v2(nd.array(x), out_type='int8')
    out, olo, ohi = nd.quantized_pooling(qx, lo, hi, kernel=(2, 2),
                                         stride=(2, 2), pool_type='max')
    ref = nd.pooling(nd.array(x), kernel=(2, 2), stride=(2, 2),
                     pool_type='max').asnumpy()
    back = nd.dequantize(out, olo, ohi).asnumpy()
    assert onp.abs(back - ref).max() < 2.0 / 127


def test_requantize_int32_to_int8():
    rs = onp.random.RandomState(5)
    x = rs.uniform(-1, 1, (4, 16)).astype('float32')
    w = rs.uniform(-1, 1, (8, 16)).astype('float32')
    qx, xlo, xhi = nd.quantize_v2(nd.array(x), out_type='int8')
    qw, wlo, whi = nd.quantize_v2(nd.array(w), out_type='int8')
    out32, olo, ohi = nd.quantized_fully_connected(
        qx, qw, None, xlo, xhi, wlo, whi, num_hidden=8, no_bias=True)
    q8, rlo, rhi = nd.requantize(out32, olo, ohi)
    assert q8.dtype == onp.int8
    back = nd.dequantize(q8, rlo, rhi).asnumpy()
    ref = x @ w.T
    assert onp.abs(back - ref).max() < 0.2


def test_entropy_threshold_reasonable():
    rs = onp.random.RandomState(6)
    # heavy-tailed data: optimal threshold should be well below the max
    arr = onp.concatenate([rs.normal(0, 1, 100000),
                           onp.array([50.0, -50.0])]).astype('float32')
    mn, mx_, th, div = _get_optimal_threshold(arr, num_bins=1001)
    assert mn < 0 < mx_
    assert th < 25.0
    assert th > 1.0


def _make_mlp():
    net = gluon.nn.HybridSequential()
    net.add(gluon.nn.Dense(32, activation='relu', in_units=20))
    net.add(gluon.nn.Dense(10, in_units=32))
    net.initialize(mx.init.Xavier())
    return net


def test_quantize_net_naive_mlp_close_to_float():
    rs = onp.random.RandomState(7)
    net = _make_mlp()
    calib = nd.array(rs.uniform(-1, 1, (16, 20)).astype('float32'))
    qnet = quantize_net(net, calib_data=calib, calib_mode='naive')
    kinds = [type(c).__name__ for c in qnet._children.values()]
    assert kinds == ['QuantizedDense', 'QuantizedDense']
    x = nd.array(rs.uniform(-1, 1, (4, 20)).astype('float32'))
    ref = net(x).asnumpy()
    out = qnet(x).asnumpy()
    assert onp.abs(out - ref).max() < 0.25 * max(1.0, onp.abs(ref).max())
    # original net untouched
    assert all(type(c).__name__ == 'Dense' for c in net._children.values())


def test_quantize_net_dynamic_mode():
    rs = onp.random.RandomState(8)
    net = _make_mlp()
    qnet = quantize_net(net, calib_mode='none')
    x = nd.array(rs.uniform(-1, 1, (4, 20)).astype('float32'))
    ref = net(x).asnumpy()
    out = qnet(x).asnumpy()
    assert onp.abs(out - ref).max() < 0.25 * max(1.0, onp.abs(ref).max())


def test_quantize_net_entropy_and_hybridize():
    rs = onp.random.RandomState(9)
    net = _make_mlp()
    calib = [nd.array(rs.uniform(-1, 1, (8, 20)).astype('float32'))
             for _ in range(3)]
    qnet = quantize_net(net, calib_data=calib, calib_mode='entropy',
                        num_bins=501)
    x = nd.array(rs.uniform(-1, 1, (4, 20)).astype('float32'))
    out_eager = qnet(x).asnumpy()
    qnet.hybridize()
    out_hyb = qnet(x).asnumpy()
    assert onp.allclose(out_eager, out_hyb, atol=1e-5)


def test_quantize_net_conv_net():
    rs = onp.random.RandomState(10)
    net = gluon.nn.HybridSequential()
    net.add(gluon.nn.Conv2D(8, kernel_size=3, padding=1, in_channels=3,
                            activation='relu'))
    net.add(gluon.nn.Conv2D(4, kernel_size=3, padding=1, in_channels=8))
    net.initialize(mx.init.Xavier())
    calib = nd.array(rs.uniform(-1, 1, (4, 3, 8, 8)).astype('float32'))
    qnet = quantize_net(net, calib_data=calib, calib_mode='naive')
    kinds = [type(c).__name__ for c in qnet._children.values()]
    assert kinds == ['QuantizedConv2D', 'QuantizedConv2D']
    x = nd.array(rs.uniform(-1, 1, (2, 3, 8, 8)).astype('float32'))
    ref = net(x).asnumpy()
    out = qnet(x).asnumpy()
    assert onp.abs(out - ref).max() < 0.3 * max(1.0, onp.abs(ref).max())


def test_quantize_net_exclude_layers():
    net = _make_mlp()
    calib = nd.array(onp.random.RandomState(11).uniform(
        -1, 1, (8, 20)).astype('float32'))
    qnet = quantize_net(net, calib_data=calib, calib_mode='naive',
                        exclude_layers=['0'])
    kinds = [type(c).__name__ for c in qnet._children.values()]
    assert kinds == ['Dense', 'QuantizedDense']


def test_quantized_pooling_uint8():
    rs = onp.random.RandomState(12)
    x = rs.uniform(0, 5, (1, 2, 4, 4)).astype('float32')
    q, lo, hi = nd.quantize(nd.array(x), 0.0, 5.0, out_type='uint8')
    out, olo, ohi = nd.quantized_pooling(q, lo, hi, kernel=(2, 2),
                                         stride=(2, 2), pool_type='max')
    assert out.dtype == onp.uint8
    ref = nd.pooling(nd.array(x), kernel=(2, 2), stride=(2, 2),
                     pool_type='max').asnumpy()
    back = nd.dequantize(out, olo, ohi).asnumpy()
    assert onp.abs(back - ref).max() < 5.0 / 255 + 1e-6
    # avg pool of bright uint8 values must not clip at 127
    bright = nd.array(onp.full((1, 1, 2, 2), 200, dtype='uint8'))
    avg, _, _ = nd.quantized_pooling(bright, 0.0, 5.0, kernel=(2, 2),
                                     stride=(2, 2), pool_type='avg')
    assert int(avg.asnumpy().ravel()[0]) == 200


def test_quantized_conv_scalar_args():
    rs = onp.random.RandomState(13)
    x = rs.uniform(-1, 1, (1, 2, 6, 6)).astype('float32')
    w = rs.uniform(-1, 1, (3, 2, 3, 3)).astype('float32')
    qx, xlo, xhi = nd.quantize_v2(nd.array(x), out_type='int8')
    qw, wlo, whi = nd.quantize_v2(nd.array(w), out_type='int8')
    out32, olo, ohi = nd.quantized_conv(
        qx, qw, None, xlo, xhi, wlo, whi, kernel=3, stride=1, pad=1,
        num_filter=3, no_bias=True)
    assert out32.shape == (1, 3, 6, 6)


def test_quantized_net_save_load_roundtrip(tmp_path):
    rs = onp.random.RandomState(14)
    net = _make_mlp()
    calib = nd.array(rs.uniform(-1, 1, (16, 20)).astype('float32'))
    qnet = quantize_net(net, calib_data=calib, calib_mode='naive')
    x = nd.array(rs.uniform(-1, 1, (4, 20)).astype('float32'))
    ref = qnet(x).asnumpy()
    fname = str(tmp_path / 'qnet.params')
    qnet.save_parameters(fname)
    # fresh conversion with different calibration, then load the saved state
    other = quantize_net(net, calib_data=nd.array(
        rs.uniform(-5, 5, (16, 20)).astype('float32')), calib_mode='naive')
    assert not onp.allclose(other(x).asnumpy(), ref)
    other.load_parameters(fname)
    assert onp.allclose(other(x).asnumpy(), ref, atol=1e-6)


def test_quantize_net_channel_wise_beats_tensor_wise():
    rs = onp.random.RandomState(15)
    net = gluon.nn.HybridSequential()
    net.add(gluon.nn.Conv2D(8, kernel_size=3, padding=1, in_channels=3))
    net.initialize(mx.init.Xavier())
    # make filter magnitudes wildly uneven: per-tensor scale wastes int8 range
    w = net._children['0'].weight.data().asnumpy().copy()
    w[0] *= 50.0
    net._children['0'].weight.set_data(nd.array(w))
    calib = nd.array(rs.uniform(-1, 1, (4, 3, 8, 8)).astype('float32'))
    x = nd.array(rs.uniform(-1, 1, (2, 3, 8, 8)).astype('float32'))
    ref = net(x).asnumpy()
    qt = quantize_net(net, calib_data=calib, calib_mode='naive')(x).asnumpy()
    qc = quantize_net(net, calib_data=calib, calib_mode='naive',
                      quantize_granularity='channel-wise')(x).asnumpy()
    # channel 0's error is dominated by (inherent) activation quantization;
    # the tensor-wise scale crushes the other channels' weights to ~0 while
    # channel-wise recovers them
    err_t = onp.abs(qt - ref)[:, 1:].max()
    err_c = onp.abs(qc - ref)[:, 1:].max()
    assert err_c < err_t * 0.2, (err_t, err_c)


def test_quantize_net_rejects_bad_args():
    net = _make_mlp()
    with pytest.raises(ValueError):
        quantize_net(net, calib_mode='none', quantize_granularity='block')
    with pytest.raises(TypeError):
        quantize_net(net, calib_mode='none', num_calib_batchs=3)  # typo


def test_quantize_net_inplace_fallback_clears_cached_op(monkeypatch):
    import types
    import mxnet_tpu.contrib.quantization as qmod
    rs = onp.random.RandomState(16)
    net = _make_mlp()
    net.hybridize()
    x = nd.array(rs.uniform(-1, 1, (4, 20)).astype('float32'))
    net(x)  # populate the compiled cache with the float graph
    def boom(*a, **k):
        raise TypeError("not deepcopyable")
    monkeypatch.setattr(qmod, 'copy', types.SimpleNamespace(deepcopy=boom))
    qnet = quantize_net(net, calib_mode='none')
    assert qnet is net  # converted in place
    kinds = [type(c).__name__ for c in qnet._children.values()]
    assert kinds == ['QuantizedDense', 'QuantizedDense']
    # the old float executable must not be reused
    out = qnet(x).asnumpy()
    assert out.shape == (4, 10)


def test_channel_wise_ranges_flow_through_int8_ops():
    """Per-channel conv output ranges compose with pooling/requantize/
    concat/add without leaving the quantized domain."""
    rs = onp.random.RandomState(17)
    x = rs.uniform(-1, 1, (2, 3, 8, 8)).astype('float32')
    w = rs.uniform(-1, 1, (4, 3, 3, 3)).astype('float32')
    w[0] *= 20.0
    qx, xlo, xhi = nd.quantize_v2(nd.array(x), out_type='int8')
    # channel-wise weight ranges
    amax = onp.abs(w).reshape(4, -1).max(axis=1)
    qw = nd.array(onp.clip(onp.round(
        w * (127.0 / amax).reshape(4, 1, 1, 1)), -127, 127).astype('int8'))
    wlo, whi = nd.array(-amax), nd.array(amax)
    out32, olo, ohi = nd.quantized_conv(
        qx, qw, None, xlo, xhi, wlo, whi, kernel=(3, 3), pad=(1, 1),
        num_filter=4, no_bias=True)
    assert olo.shape == (4, 1, 1)
    q8, rlo, rhi = nd.requantize(out32, olo, ohi)
    p, plo, phi = nd.quantized_pooling(q8, rlo, rhi, kernel=(2, 2),
                                       stride=(2, 2), pool_type='max')
    c, clo, chi = nd.quantized_concat(p, plo, phi, p, plo, phi, dim=1)
    a, alo, ahi = nd.quantized_elemwise_add(p, p, plo, phi, plo, phi)
    f, flo, fhi = nd.quantized_flatten(p, plo, phi)
    ref = nd.pooling(nd.convolution(nd.array(x), nd.array(w), kernel=(3, 3),
                                    pad=(1, 1), num_filter=4, no_bias=True),
                     kernel=(2, 2), stride=(2, 2), pool_type='max').asnumpy()
    back = nd.dequantize(p, plo, phi).asnumpy()
    assert onp.abs(back - ref).max() < 0.05 * max(1.0, onp.abs(ref).max())
