"""Deterministic data resharding across elastic world changes (the
scale-UP PR's data plane): the ElasticShard exactly-once guarantee
across any shrink->grow chain, the checkpoint-manifest round-trip of
the data position, and the iterator/sampler wiring on top."""
import numpy as onp
import pytest

import mxnet_tpu as mx
from mxnet_tpu import checkpoint, gluon
from mxnet_tpu.base import MXNetError
from mxnet_tpu.gluon.data import DataLoader, ElasticSampler
from mxnet_tpu.io import ElasticShard, NDArrayIter

G, N = 8, 32     # global batch / dataset size (4 batches per epoch)


def _reference_batches(steps, seed=5):
    """Fixed-world (world=1) sample order: the ground truth every
    elastic history must re-partition without loss or duplication."""
    ref = ElasticShard(N, G, rank=0, world=1, seed=seed)
    return [[ref.sample_at(s * G + j) for j in range(G)]
            for s in range(steps)]


def test_elastic_shard_exactly_once_across_shrink_grow():
    """dp=4 -> 2 -> 4 mid-epoch: concatenating every rank's block per
    step reproduces the fixed-world batches sample-for-sample — no
    sample dropped, none double-seen, across epoch boundaries too."""
    seen = []

    def run(world, steps, state=None):
        shards = [ElasticShard.from_state(state, rank=r, world=world)
                  if state is not None else
                  ElasticShard(N, G, rank=r, world=world, seed=5)
                  for r in range(world)]
        for _ in range(steps):
            batch = []
            for sh in shards:
                batch.extend(sh.next_batch())
            seen.append(batch)
        return shards[0].state()

    st = run(4, 3)
    st = run(2, 3, st)          # shrink mid-epoch
    run(4, 4, st)               # grow back, crossing into epoch 2
    want = _reference_batches(10)
    assert len(seen) == 10
    for s in range(10):
        # block order IS the world-indexed assignment: rank r owns
        # [r*G/w, (r+1)*G/w) of the global batch, so the rank-ordered
        # concatenation equals the fixed-world batch exactly
        assert seen[s] == want[s], f"step {s + 1} diverged"


def test_elastic_shard_epochwise_shuffle_is_a_permutation():
    sh = ElasticShard(N, G, rank=0, world=1, seed=9)
    epoch0 = [x for _ in range(N // G) for x in sh.next_batch()]
    epoch1 = [x for _ in range(N // G) for x in sh.next_batch()]
    assert sorted(epoch0) == list(range(N))
    assert sorted(epoch1) == list(range(N))
    assert epoch0 != epoch1          # reshuffled per epoch
    assert sh.epoch == 2


def test_elastic_shard_rejects_indivisible_world():
    with pytest.raises(MXNetError, match='not\\s+divisible'):
        ElasticShard(N, G, rank=0, world=3)
    sh = ElasticShard(N, G, rank=0, world=2)
    with pytest.raises(MXNetError, match='not\\s+divisible'):
        sh.reshard(0, 3)
    # the failed reshard must not have corrupted the old assignment
    assert sh.world == 2 and sh.batch_size == G // 2


def _tiny(prefix='rs'):
    net = gluon.nn.Dense(2, in_units=1, prefix=f'{prefix}_')
    net.initialize(mx.init.Xavier())
    return net


def test_manifest_data_position_round_trip(tmp_path):
    """The commit manifest carries the data position next to the world
    metadata; a restore into a DIFFERENT world replays the exact
    remaining samples (dp=4 -> 2 -> 4)."""
    net = _tiny()
    mgr = checkpoint.CheckpointManager(str(tmp_path), params=net,
                                       async_save=False)
    shard = ElasticShard(N, G, rank=0, world=4, seed=3)
    mgr.bind_data_state(lambda: shard.state())
    for _ in range(3):
        shard.next_batch()
    mgr.save(3)

    # restore at world 2: position survives verbatim, block re-splits
    net2 = _tiny()
    mgr2 = checkpoint.CheckpointManager(str(tmp_path), params=net2,
                                        async_save=False)
    assert mgr2.restore_latest() == 3
    ds = mgr2.last_restored_metadata['data']
    assert ds['position'] == 3 * G and ds['world'] == 4
    assert ds['assignment']['0'] == [0, G // 4]
    halves = [ElasticShard.from_state(ds, rank=r, world=2)
              for r in range(2)]
    want = _reference_batches(8, seed=3)
    got4 = [x for sh in halves for x in sh.next_batch()]
    assert got4 == want[3]           # step 4: exact remaining samples

    # grow back to 4 from the SAME manifest state advanced one step
    st = halves[0].state()
    quarters = [ElasticShard.from_state(st, rank=r, world=4)
                for r in range(4)]
    got5 = [x for sh in quarters for x in sh.next_batch()]
    assert got5 == want[4]           # step 5: still sample-for-sample

    # the world metadata the manifest already records sits alongside
    ck = mgr2.restore(3, apply=False)
    assert 'world' in ck.metadata and 'data' in ck.metadata


def test_ndarrayiter_shard_stream(tmp_path):
    """NDArrayIter with an ElasticShard: per-rank batches follow the
    shard's world-indexed ids, reset() does NOT rewind the stream, and
    data_state()/reshard() round-trip the position."""
    x = onp.arange(N, dtype=onp.float32).reshape(N, 1)
    it = NDArrayIter(x, shard=ElasticShard(N, G, rank=1, world=2,
                                           seed=5, shuffle=False))
    assert it.batch_size == G // 2
    b1 = it.next()
    # rank 1 of 2 owns the second half-block of samples [0, G)
    assert b1.data[0].asnumpy().ravel().tolist() == [4.0, 5.0, 6.0, 7.0]
    it.reset()
    b2 = it.next()
    # a new pass continues the STREAM: position was not rewound
    assert b2.data[0].asnumpy().ravel().tolist() == [12.0, 13.0, 14.0,
                                                     15.0]
    st = it.data_state()
    assert st['position'] == 2 * G
    it.reshard(0, 4)
    assert it.batch_size == G // 4
    b3 = it.next()
    assert b3.data[0].asnumpy().ravel().tolist() == [16.0, 17.0]


def test_dataloader_elastic_sampler_round_trip():
    """DataLoader(batch_sampler=ElasticSampler): world-indexed batches,
    manifest state through data_state(), reshard() re-partitions."""
    from mxnet_tpu.gluon.data import ArrayDataset
    x = onp.arange(N, dtype=onp.float32).reshape(N, 1)
    ds = ArrayDataset(x)
    smp = ElasticSampler(N, G, rank=0, world=2, seed=0, shuffle=False)
    dl = DataLoader(ds, batch_sampler=smp)
    batches = [b.asnumpy().ravel().tolist() for b in dl]
    assert batches[0] == [0.0, 1.0, 2.0, 3.0]        # first half-block
    st = dl.data_state()
    assert st['position'] == N                       # one epoch drawn
    dl.reshard(1, 2)
    nxt = next(iter(dl)).asnumpy().ravel().tolist()
    assert nxt == [4.0, 5.0, 6.0, 7.0]               # other half now
    with pytest.raises(MXNetError, match='not elastic'):
        DataLoader(ds, batch_size=4).reshard(0, 1)


def test_churn_kill_schedule_deterministic():
    """The churn drill's randomized kill steps come from the fault
    registry's hash stream: same seed -> same storm, any process."""
    from mxnet_tpu.resilience.faults import _unit
    a = [_unit(23, c) for c in range(6)]
    assert a == [_unit(23, c) for c in range(6)]     # deterministic
    assert all(0.0 <= u < 1.0 for u in a)
    assert len(set(a)) == 6                          # and spread out
    assert a != [_unit(24, c) for c in range(6)]     # seed-sensitive
