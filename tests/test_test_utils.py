"""The test_utils helper library itself (ref: python/mxnet/test_utils.py,
~95 helpers backing the reference's entire unit-test suite)."""
import numpy as onp
import pytest

import mxnet_tpu as mx
from mxnet_tpu import test_utils as tu


def test_sparse_generators():
    arr, dense = tu.rand_sparse_ndarray((16, 10), 'csr', density=0.3)
    assert arr.stype == 'csr'
    onp.testing.assert_allclose(arr.asnumpy(), dense)
    nnz_frac = (dense != 0).mean()
    assert 0.05 < nnz_frac < 0.6

    arr, dense = tu.rand_sparse_ndarray((12, 6), 'row_sparse', density=0.5)
    assert arr.stype == 'row_sparse'
    onp.testing.assert_allclose(arr.asnumpy(), dense)

    pl, dense = tu.rand_sparse_ndarray((8, 16), 'csr', density=0.2,
                                       distribution='powerlaw')
    d = pl.asnumpy()
    # powerlaw: first row populated, row nnz non-increasing after doubling
    assert (d[0] != 0).sum() >= 1


def test_create_sparse_array_modifier_and_zd():
    arr = tu.create_sparse_array((10, 8), 'csr', density=0.4,
                                 modifier_func=lambda x: 2.0)
    d = arr.asnumpy()
    assert set(onp.unique(d)).issubset({0.0, 2.0})
    z = tu.create_sparse_array_zd((10, 8), 'csr', density=0)
    assert (z.asnumpy() == 0).all()


def test_shuffle_csr_column_indices_preserves_value():
    arr, dense = tu.rand_sparse_ndarray((10, 12), 'csr', density=0.3)
    shuffled = tu.shuffle_csr_column_indices(arr)
    onp.testing.assert_allclose(shuffled.asnumpy(), dense)


def test_chi_square_check_uniform():
    rng = onp.random.RandomState(0)
    chi2, counts = tu.chi_square_check(
        lambda n: rng.randint(0, 4, n), buckets=[0, 1, 2, 3],
        probs=[0.25] * 4, nsamples=40000)
    assert chi2 < 20, chi2
    assert counts.sum() == 40000


def test_chi_square_check_interval_buckets():
    rng = onp.random.RandomState(0)
    chi2, _ = tu.chi_square_check(
        lambda n: rng.rand(n), buckets=[(0, .5), (.5, 1.0)],
        probs=[0.5, 0.5], nsamples=20000)
    assert chi2 < 15


def test_get_mnist_and_iterator():
    m = tu.get_mnist()
    assert m['train_data'].shape[1:] == (1, 28, 28)
    assert m['train_label'].max() <= 9
    train, val = tu.get_mnist_iterator(32)
    batch = next(iter(train))
    assert batch.data[0].shape == (32, 1, 28, 28)


def test_same_symbol_structure():
    from mxnet_tpu import sym
    def build():
        x = sym.Variable('x')
        return sym.Activation(sym.FullyConnected(
            x, num_hidden=4, name='fc'), act_type='relu')
    assert tu.same_symbol_structure(build(), build())
    x = sym.Variable('x')
    other = sym.FullyConnected(x, num_hidden=4, name='fc')
    assert not tu.same_symbol_structure(build(), other)


def test_env_and_context_helpers():
    prev = tu.set_env_var('MXTPU_TEST_ENV_VAR', 'yes')
    assert tu.EnvManager is not None
    import os
    assert os.environ['MXTPU_TEST_ENV_VAR'] == 'yes'
    os.environ.pop('MXTPU_TEST_ENV_VAR', None)
    assert tu.get_etol() == 0.0 and tu.get_etol(0.1) == 0.1
    assert tu.has_tvm_ops() is False
    assert tu.is_op_runnable() is True
    assert isinstance(tu.list_gpus(), list)
    tu.set_default_context(mx.cpu(0))
    assert tu.default_context().device_type == 'cpu'


def test_matrix_generators():
    m = tu.new_sym_matrix_with_real_eigvals_2d(5)
    onp.testing.assert_allclose(m, m.T)
    q = tu.new_orthonormal_matrix_2d(4)
    onp.testing.assert_allclose(q @ q.T, onp.eye(4), atol=1e-5)
    a = tu.new_matrix_with_real_eigvals_2d(4)
    assert onp.abs(onp.linalg.eigvals(a).imag).max() < 1e-5
    b = tu.new_matrix_with_real_eigvals_nd(3, ndim=2)
    assert b.shape == (2, 3, 3)


def test_parse_location_and_shapes():
    from mxnet_tpu import sym
    x = sym.Variable('a')
    s = sym.sin(x)
    loc = tu._parse_location(s, {'a': onp.ones((2, 2), onp.float32)})
    assert set(loc) == {'a'}
    with pytest.raises(ValueError):
        tu._parse_location(s, {'bogus': onp.ones((2, 2))})
    tu.check_shapes((2, 3), (2, 3))
    with pytest.raises(AssertionError):
        tu.check_shapes((2, 3), (3, 2))
