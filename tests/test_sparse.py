"""Sparse NDArray + sparse training tests
(ref: tests/python/unittest/test_sparse_ndarray.py, test_sparse_operator.py,
tests for lazy_update in test_optimizer.py)."""
import numpy as onp

import mxnet_tpu as mx
from mxnet_tpu import nd, gluon, autograd
from mxnet_tpu.ndarray import sparse as sp


def _rand_sparse(shape, density, rs):
    a = rs.uniform(-1, 1, shape).astype('float32')
    a[rs.uniform(0, 1, shape) > density] = 0
    return a


def test_csr_parts_roundtrip():
    rs = onp.random.RandomState(0)
    a = _rand_sparse((7, 11), 0.3, rs)
    csr = sp.csr_matrix(a)
    data, indices, indptr = (csr.data.asnumpy(), csr.indices.asnumpy(),
                             csr.indptr.asnumpy())
    rebuilt = sp.csr_matrix((data, indices, indptr), shape=a.shape)
    assert onp.allclose(rebuilt.asnumpy(), a)
    assert rebuilt.stype == 'csr'
    # indptr is monotone and counts all nonzeros
    assert indptr[0] == 0 and indptr[-1] == (a != 0).sum()
    assert (onp.diff(indptr) >= 0).all()


def test_csr_empty_rows():
    a = onp.zeros((4, 5), dtype='float32')
    a[2, 3] = 2.5
    csr = sp.csr_matrix(a)
    assert onp.allclose(csr.indptr.asnumpy(), [0, 0, 0, 1, 1])
    assert csr.indices.asnumpy().tolist() == [3]


def test_row_sparse_roundtrip():
    rs = onp.random.RandomState(1)
    data = rs.uniform(-1, 1, (3, 4)).astype('float32')
    indices = onp.array([1, 4, 6])
    rsp = sp.row_sparse_array((data, indices), shape=(8, 4))
    assert rsp.stype == 'row_sparse'
    assert rsp.indices.asnumpy().tolist() == [1, 4, 6]
    assert onp.allclose(rsp.data.asnumpy(), data)
    dense = rsp.tostype('default')
    assert dense.stype == 'default'
    assert onp.allclose(dense.asnumpy()[indices], data)


def test_retain():
    rs = onp.random.RandomState(2)
    a = rs.uniform(1, 2, (6, 3)).astype('float32')
    rsp = sp.row_sparse_array(a)
    kept = sp.retain(rsp, nd.array(onp.array([0, 5])))
    out = kept.asnumpy()
    assert onp.allclose(out[[0, 5]], a[[0, 5]])
    assert (out[1:5] == 0).all()


def test_sparse_dot_matches_dense():
    rs = onp.random.RandomState(3)
    a = _rand_sparse((5, 8), 0.4, rs)
    b = rs.uniform(-1, 1, (8, 3)).astype('float32')
    out = sp.dot(sp.csr_matrix(a), nd.array(b))
    assert onp.allclose(out.asnumpy(), a @ b, atol=1e-5)


def test_density():
    a = onp.zeros((4, 4), dtype='float32')
    a[0, 0] = 1
    assert abs(sp.csr_matrix(a).density - 1 / 16) < 1e-9


def test_lazy_sgd_mom_skips_absent_rows():
    rs = onp.random.RandomState(4)
    w0 = rs.uniform(-1, 1, (6, 4)).astype('float32')
    g = onp.zeros((6, 4), dtype='float32')
    g[[1, 3]] = rs.uniform(-1, 1, (2, 4))

    opt = mx.optimizer.SGD(learning_rate=0.1, momentum=0.9, wd=0.01,
                           lazy_update=True)
    # row_sparse grad: absent rows untouched (weight AND momentum)
    w = nd.array(w0.copy())
    grad = sp.RowSparseNDArray(nd.array(g)._data)
    state = opt.create_state(0, w)
    opt.update(0, w, grad, state)
    wn = w.asnumpy()
    assert onp.allclose(wn[[0, 2, 4, 5]], w0[[0, 2, 4, 5]])
    assert not onp.allclose(wn[[1, 3]], w0[[1, 3]])
    assert (state.asnumpy()[[0, 2, 4, 5]] == 0).all()

    # dense grad with identical values: every row updated (wd decay applies)
    w2 = nd.array(w0.copy())
    state2 = opt.create_state(1, w2)
    opt.update(1, w2, nd.array(g), state2)
    assert not onp.allclose(w2.asnumpy()[[0, 2]], w0[[0, 2]])


def test_lazy_adam_state_frozen_for_absent_rows():
    rs = onp.random.RandomState(5)
    w0 = rs.uniform(-1, 1, (5, 3)).astype('float32')
    g = onp.zeros((5, 3), dtype='float32')
    g[0] = 1.0

    opt = mx.optimizer.Adam(learning_rate=0.05, lazy_update=True)
    w = nd.array(w0.copy())
    state = opt.create_state(0, w)
    grad = sp.RowSparseNDArray(nd.array(g)._data)
    for _ in range(3):
        opt.update(0, w, grad, state)
    mean, var = state
    assert onp.allclose(w.asnumpy()[1:], w0[1:])
    assert (mean.asnumpy()[1:] == 0).all()
    assert (var.asnumpy()[1:] == 0).all()
    assert not onp.allclose(w.asnumpy()[0], w0[0])


def test_embedding_sparse_grad_end_to_end():
    """Embedding with sparse_grad trains only touched rows under lazy SGD
    (ref: test_module.py sparse embedding tests)."""
    vocab, dim = 10, 4
    emb = gluon.nn.Embedding(vocab, dim, sparse_grad=True)
    emb.initialize(mx.init.Normal(0.1))
    w0 = emb.weight.data().asnumpy().copy()
    trainer = gluon.Trainer(emb.collect_params(), 'sgd',
                            {'learning_rate': 0.5, 'momentum': 0.9})
    x = nd.array(onp.array([1, 3, 3], dtype='float32'))
    with autograd.record():
        y = emb(x)
        loss = (y * y).sum()
    loss.backward()
    assert emb.weight.grad().stype == 'row_sparse'
    trainer.step(1)
    w1 = emb.weight.data().asnumpy()
    untouched = [i for i in range(vocab) if i not in (1, 3)]
    assert onp.allclose(w1[untouched], w0[untouched])
    assert not onp.allclose(w1[[1, 3]], w0[[1, 3]])


def test_kvstore_row_sparse_pull():
    kv = mx.kv.create('local')
    rs = onp.random.RandomState(6)
    a = rs.uniform(-1, 1, (8, 3)).astype('float32')
    kv.init('w', sp.row_sparse_array(a))
    out = sp.zeros('row_sparse', (8, 3))
    kv.row_sparse_pull('w', out=out, row_ids=nd.array(onp.array([2, 5])))
    got = out.asnumpy()
    assert onp.allclose(got[[2, 5]], a[[2, 5]], atol=1e-6)
    assert (got[[0, 1, 3, 4, 6, 7]] == 0).all()


def test_sparse_grad_is_row_sparse_ndarray():
    emb = gluon.nn.Embedding(6, 3, sparse_grad=True)
    emb.initialize(mx.init.Normal(0.1))
    x = nd.array(onp.array([0, 2], dtype='float32'))
    with autograd.record():
        loss = (emb(x) ** 2).sum()
    loss.backward()
    g = emb.weight.grad()
    assert isinstance(g, sp.RowSparseNDArray)
    assert g.stype == 'row_sparse'
    assert sorted(g.indices.asnumpy().tolist()) == [0, 2]


def test_dot_csr_dense_storage_dispatch():
    """nd.dot with a CSR lhs routes through the BCOO sparse kernel
    (FComputeEx storage-driven dispatch, op_attr_types.h:304) and matches
    the dense result."""
    import numpy as onp
    from mxnet_tpu import nd
    from mxnet_tpu.ndarray import sparse
    from mxnet_tpu.ops import sparse_ops

    rng = onp.random.RandomState(0)
    dense = rng.randn(8, 6).astype('float32')
    dense[dense < 0.5] = 0.0
    csr = sparse.csr_matrix(dense)
    rhs = nd.array(rng.randn(6, 4).astype('float32'))

    before = sparse_ops.route_counts['dot_csr_dense']
    out = nd.dot(csr, rhs)
    assert sparse_ops.route_counts['dot_csr_dense'] == before + 1
    onp.testing.assert_allclose(out.asnumpy(), dense @ rhs.asnumpy(),
                                rtol=1e-5, atol=1e-5)
    # dense lhs still takes the dense kernel
    out2 = nd.dot(nd.array(dense), rhs)
    assert sparse_ops.route_counts['dot_csr_dense'] == before + 1
    onp.testing.assert_allclose(out2.asnumpy(), out.asnumpy(),
                                rtol=1e-5, atol=1e-5)


def test_dot_csr_dense_under_autograd():
    """The sparse route survives autograd recording: the nnz budget is
    computed eagerly before tracing, and gradients flow to the dense
    operand (regression: TracerArrayConversionError when counting nnz on
    a traced array)."""
    import numpy as onp
    from mxnet_tpu import nd, autograd
    from mxnet_tpu.ndarray import sparse

    rng = onp.random.RandomState(1)
    dense = rng.randn(6, 5).astype('float32')
    dense[dense < 0.6] = 0.0
    csr = sparse.csr_matrix(dense)
    W = nd.array(rng.randn(5, 3).astype('float32'))
    W.attach_grad()
    with autograd.record():
        out = nd.dot(csr, W)
        loss = nd.sum(out)
    loss.backward()
    onp.testing.assert_allclose(
        W.grad.asnumpy(), (dense.T @ onp.ones((6, 3), 'float32')),
        rtol=1e-5, atol=1e-5)


def test_csr_parts_cached_per_payload():
    """VERDICT r4 #9: accessors must compute compressed parts once per
    payload mutation, not on every .data/.indices/.indptr access."""
    import numpy as onp
    from mxnet_tpu.ndarray import sparse as sp

    a = sp.csr_matrix(onp.asarray([[1.0, 0.0], [0.0, 2.0]]))
    calls = {'n': 0}
    orig = onp.nonzero

    def counting_nonzero(*args, **kwargs):
        calls['n'] += 1
        return orig(*args, **kwargs)

    onp.nonzero = counting_nonzero
    try:
        _ = a.data, a.indices, a.indptr, a.data
        assert calls['n'] == 1, calls['n']
        # payload mutation rebinds ._data → exactly one recompute
        a[:] = onp.asarray([[0.0, 3.0], [4.0, 0.0]])
        idx = a.indices.asnumpy()
        ptr = a.indptr.asnumpy()
        _ = a.data
        assert calls['n'] == 2, calls['n']
    finally:
        onp.nonzero = orig
    onp.testing.assert_array_equal(idx, [1, 0])
    onp.testing.assert_array_equal(ptr, [0, 1, 2])


def test_rowsparse_parts_cached_and_correct():
    import numpy as onp
    from mxnet_tpu.ndarray import sparse as sp

    r = sp.row_sparse_array(onp.asarray([[0.0, 0.0], [5.0, 6.0]]))
    onp.testing.assert_array_equal(r.indices.asnumpy(), [1])
    onp.testing.assert_array_equal(r.data.asnumpy(), [[5.0, 6.0]])
    import copy
    r2 = copy.deepcopy(r)   # deepcopy must carry sparse slots (MRO walk)
    assert r2.stype == 'row_sparse'
    onp.testing.assert_array_equal(r2.indices.asnumpy(), [1])
