"""AMP tests (ref: tests/python/gpu/test_contrib_amp.py, bf16 target)."""
import numpy as onp
import pytest

import mxnet_tpu as mx
from mxnet_tpu import nd, gluon, autograd, amp


@pytest.fixture
def amp_on():
    amp.init()
    yield
    from mxnet_tpu.amp import amp as _amp_mod
    _amp_mod._deinit()


def test_autocast_matmul_bf16(amp_on):
    a = nd.array(onp.random.rand(8, 16).astype(onp.float32))
    b = nd.array(onp.random.rand(16, 4).astype(onp.float32))
    out = nd.dot(a, b)
    assert str(out.dtype) == 'bfloat16'
    # fp32-pinned op promotes back up
    sm = nd.softmax(out)
    assert str(sm.dtype) == 'float32'


def test_autocast_widest(amp_on):
    a = nd.array(onp.ones((4, 4), onp.float32)).astype('bfloat16')
    b = nd.array(onp.ones((4, 4), onp.float32))
    out = nd.broadcast_add(a, b)
    assert str(out.dtype) == 'float32'


def test_amp_training_converges(amp_on):
    """Dense layer under autocast: fwd in bf16, master weights fp32,
    loss decreases."""
    net = gluon.nn.Dense(1)
    net.initialize(mx.init.Xavier())
    trainer = gluon.Trainer(net.collect_params(), 'sgd',
                            {'learning_rate': 0.1})
    amp.init_trainer(trainer)
    loss_fn = gluon.loss.L2Loss()
    rng = onp.random.RandomState(0)
    X = rng.rand(64, 4).astype(onp.float32)
    W = onp.array([[1.0], [-2.0], [3.0], [0.5]], onp.float32)
    Y = X @ W
    x, y = nd.array(X), nd.array(Y)
    first = last = None
    for _ in range(100):
        with autograd.record():
            out = net(x)
            loss = loss_fn(out, y)
            # scale_loss nests inside record() (ref: AMP tutorial usage)
            with amp.scale_loss(loss, trainer) as scaled:
                pass
        scaled.backward()
        trainer.step(64)
        last = float(loss.mean().asnumpy())
        if first is None:
            first = last
    # bf16 forward puts a precision floor under the loss; 5x reduction
    # demonstrates the fp32 master weights are updating correctly
    assert last < first * 0.2, (first, last)
    # master weights stayed fp32
    assert str(net.weight.data().dtype) == 'float32'


def test_loss_scaler_overflow_skips_update():
    from mxnet_tpu.amp import LossScaler
    s = LossScaler(init_scale=1024., scale_window=2)
    s.update_scale(overflow=True)
    assert s.loss_scale == 512.
    s.update_scale(False)
    s.update_scale(False)
    assert s.loss_scale == 1024.


def test_trainer_skips_on_nonfinite_grad():
    net = gluon.nn.Dense(1)
    net.initialize()
    trainer = gluon.Trainer(net.collect_params(), 'sgd',
                            {'learning_rate': 0.1})
    amp.init_trainer(trainer, loss_scale=1024.)
    x = nd.array(onp.ones((2, 3), onp.float32))
    with autograd.record():
        loss = (net(x) * onp.inf).sum()
    loss.backward()
    w_before = net.weight.data().asnumpy().copy()
    trainer.step(2)
    onp.testing.assert_array_equal(net.weight.data().asnumpy(), w_before)
    assert trainer._amp_loss_scaler.loss_scale == 512.


def test_convert_hybrid_block():
    net = gluon.nn.HybridSequential()
    net.add(gluon.nn.Dense(8, activation='relu'))
    net.add(gluon.nn.BatchNorm())
    net.add(gluon.nn.Dense(2))
    net.initialize()
    x = nd.array(onp.random.RandomState(1).rand(4, 6).astype(onp.float32))
    ref = net(x).asnumpy()

    conv = amp.convert_hybrid_block(net)
    out = conv(x)
    assert str(out.dtype) == 'float32'
    onp.testing.assert_allclose(out.asnumpy(), ref, atol=5e-2, rtol=5e-2)
    # conversion is non-destructive: original stays fp32
    for _, p in net.collect_params().items():
        assert str(p.data().dtype) == 'float32'
    # converted copy: dense weights bf16, norm stats fp32
    params = conv.collect_params()
    dense_w = [p for n, p in params.items() if n.endswith('weight')][0]
    assert str(dense_w.data().dtype) == 'bfloat16'
    bn_mean = [p for n, p in params.items() if 'running_mean' in n
               or 'moving_mean' in n]
    if bn_mean:
        assert str(bn_mean[0].data().dtype) == 'float32'
