"""Error-feedback gradient compression + hierarchy-aware collectives
(ISSUE 12): the codec contracts, the in-step quantization epilogue with
per-param sharded residuals, the (cross-host, intra-host) dp
decomposition and its per-hop wire accounting, composition with
ZeRO-1/3 and the non-finite guard, and checkpoint round-trips of the
residual state across dp degrees and compression configs."""
import os
import pickle
import subprocess
import sys

import numpy as onp
import pytest

import jax.numpy as jnp

import mxnet_tpu as mx
from mxnet_tpu import nd, gluon, autograd, telemetry
from mxnet_tpu.base import MXNetError
from mxnet_tpu.gluon import nn
from mxnet_tpu.parallel import make_mesh, ShardedTrainStep
from mxnet_tpu.parallel import compression as codecs
from mxnet_tpu.parallel import dist as pdist
from mxnet_tpu.resilience import NonFiniteGuard, faults


def _data(n=64, din=16, classes=8, seed=0):
    rng = onp.random.RandomState(seed)
    x = rng.randn(n, din).astype(onp.float32)
    y = rng.randint(0, classes, n).astype(onp.float32)
    return nd.array(x), nd.array(y)


def _net(din=16, hidden=32, classes=8):
    mx.random.seed(0)
    net = nn.HybridSequential()
    net.add(nn.Dense(hidden, activation='relu', in_units=din))
    net.add(nn.Dense(classes, in_units=hidden))
    net.initialize(mx.init.Xavier())
    return net


def _run(compression=None, hierarchy=None, zero=1, steps=3, dp=8,
         lr=0.01, net=None, optimizer='adamw'):
    net = net if net is not None else _net()
    loss_fn = gluon.loss.SoftmaxCrossEntropyLoss()
    step = ShardedTrainStep(net, loss_fn, optimizer,
                            {'learning_rate': lr},
                            mesh=make_mesh((dp,), ('dp',)), zero=zero,
                            compression_params=compression,
                            hierarchy=hierarchy)
    x, y = _data()
    losses = [float(step(x, y).asscalar()) for _ in range(steps)]
    return net, step, losses


# ---------------------------------------------------------------------------
# codec unit contracts
# ---------------------------------------------------------------------------

def test_codec_roundtrip_properties():
    rng = onp.random.RandomState(0)
    x = jnp.asarray(rng.randn(4, 512).astype(onp.float32))
    # fp16: bounded relative error
    dec = codecs.encode_decode(x, 'fp16')
    err = onp.abs(onp.asarray(dec) - onp.asarray(x))
    assert float(onp.max(err / (onp.abs(onp.asarray(x)) + 1e-8))) < 1e-3
    # int8: error bounded by half a quantization step of the block max
    dec = codecs.encode_decode(x, 'int8', block=256)
    err = onp.abs(onp.asarray(dec) - onp.asarray(x))
    assert float(onp.max(err)) <= float(onp.max(onp.abs(x))) / 127.0
    # 2bit with block scale: exactly three levels per block {-ts, 0, ts}
    dec = onp.asarray(codecs.encode_decode(x, '2bit', threshold=0.5,
                                           block=256))
    blocks = dec.reshape(4, 2, 256)
    src = onp.asarray(x).reshape(4, 2, 256)
    for i in range(4):
        for b in range(2):
            t = 0.5 * onp.max(onp.abs(src[i, b]))
            allowed = onp.array([-t, 0.0, t], onp.float32)
            d = onp.min(onp.abs(blocks[i, b][:, None] - allowed), axis=-1)
            assert onp.all(d < 1e-6), (i, b, t)
    # 2bit block=0: the reference's ABSOLUTE threshold
    dec = onp.asarray(codecs.encode_decode(
        jnp.asarray([0.3, 0.7, -0.6, -0.2], jnp.float32), '2bit',
        threshold=0.5, block=0))
    assert onp.allclose(dec, [0.0, 0.5, -0.5, 0.0])


def test_codec_nan_propagates_to_decoded():
    """A comparison against NaN is False, so a naive quantizer maps a
    poisoned gradient to 0 and hides it from the guard — the codecs
    must re-inject non-finite inputs into the decoded output."""
    x = jnp.asarray([1.0, float('nan'), float('inf'), -2.0], jnp.float32)
    for ctype in ('fp16', 'int8', '2bit'):
        dec = onp.asarray(codecs.encode_decode(x, ctype))
        assert onp.isnan(dec[1]), ctype
        assert not onp.isfinite(dec[2]), ctype
        assert onp.isfinite(dec[0]) and onp.isfinite(dec[3]), ctype


def test_codec_wire_bytes_math():
    # fp16: 2 bytes/elem, no scales
    assert codecs.wire_bytes((4, 512), 'fp16') == 2 * 4 * 512
    # int8: 1 byte/elem + one fp32 scale per 256-block
    assert codecs.wire_bytes((4, 512), 'int8', 256) == \
        4 * 512 + 4 * (4 * 512 // 256)
    # 2bit: 2 bits/elem + scales
    assert codecs.wire_bytes((4, 512), '2bit', 256) == \
        (4 * 512 * 2 + 7) // 8 + 4 * (4 * 512 // 256)
    # 2bit absolute threshold (block=0): no scales on the wire
    assert codecs.wire_bytes((4, 512), '2bit', 0) == (4 * 512 * 2 + 7) // 8
    # ragged last dim: one per-tensor scale
    assert codecs.wire_bytes((7,), 'int8', 256) == 7 + 4
    assert codecs.wire_bytes((), 'fp16') == 2
    assert codecs.wire_bytes((4, 512), 'none') == 4 * 4 * 512
    assert codecs.compression_ratio((4, 512), '2bit', 0) > 15.9


def test_resolve_validates_and_reads_knobs(monkeypatch):
    assert codecs.resolve(None) is None
    assert codecs.resolve({'type': 'none'}) is None
    spec = codecs.resolve({'type': '2bit', 'threshold': 0.25,
                           'block_size': 128})
    assert spec == {'type': '2bit', 'threshold': 0.25, 'block': 128}
    with pytest.raises(MXNetError, match='not supported'):
        codecs.resolve({'type': '3bit'})
    with pytest.raises(MXNetError, match='threshold'):
        codecs.resolve({'type': '2bit', 'threshold': 0})
    monkeypatch.setenv('MXTPU_COMPRESSION', 'fp16')
    spec = codecs.resolve(None)
    assert spec['type'] == 'fp16'
    # the env default reaches the step too
    net, step, losses = _run(steps=1)
    assert step.compression is not None and \
        step.compression['type'] == 'fp16'
    monkeypatch.delenv('MXTPU_COMPRESSION')
    assert codecs.resolve(None) is None


def test_error_feedback_reconstruction_invariant():
    """acc = decoded + residual EXACTLY (the EF bookkeeping identity),
    and over repeated pushes of the same gradient the accumulated
    residual eventually releases sub-threshold mass (the Deep Gradient
    Compression property)."""
    from mxnet_tpu.kvstore.gradient_compression import GradientCompression
    gc = GradientCompression('2bit', threshold=0.5)
    g = nd.array([0.3, 0.7, -0.6, -0.2])
    out1 = gc.compress_decompress(g, 'k')
    r1 = onp.asarray(gc._residual['k'])
    assert onp.allclose(out1.asnumpy() + r1, [0.3, 0.7, -0.6, -0.2])
    out2 = gc.compress_decompress(g, 'k').asnumpy()
    # 0.3 + 0.3 carried residual = 0.6 >= t -> released on push 2
    assert onp.allclose(out2, [0.5, 0.5, -0.5, 0.0])
    gc.reset()
    assert not gc._residual


def test_transient_nan_does_not_poison_eager_residual():
    """A single non-finite gradient on the eager compression paths
    (Trainer in-place / kvstore push / Module.update) must propagate to
    the DECODED value (so the guard/AMP scaler skips the step) but must
    NOT outlive the push in the carried residual — the same gated
    writeback the pjit step applies on device."""
    from mxnet_tpu.kvstore.gradient_compression import GradientCompression
    gc = GradientCompression('2bit', threshold=0.5)
    gc.compress_decompress(nd.array([0.3, 0.7]), 'k')
    r_before = onp.asarray(gc._residual['k']).copy()
    bad = gc.compress_decompress(nd.array([float('nan'), 1.0]), 'k')
    assert not onp.all(onp.isfinite(bad.asnumpy()))   # caller sees it
    assert onp.array_equal(onp.asarray(gc._residual['k']), r_before)
    # recovery: the next finite push behaves as if the bad one never
    # happened
    out = gc.compress_decompress(nd.array([0.3, 0.7]), 'k').asnumpy()
    assert onp.all(onp.isfinite(out))


def test_gradient_compression_validates_block_size():
    """The kvstore wrapper shares resolve()'s validation: a negative
    block must fail actionably at construction, not as an opaque
    reshape error mid-training."""
    from mxnet_tpu.kvstore.gradient_compression import GradientCompression
    with pytest.raises(MXNetError, match='block_size'):
        GradientCompression('int8', block_size=-64)
    with pytest.raises(MXNetError, match='threshold'):
        GradientCompression('2bit', threshold=-1.0)
    gc = GradientCompression('none', threshold=0.25)
    assert gc.type == 'none'


# ---------------------------------------------------------------------------
# host-topology query / hierarchy derivation
# ---------------------------------------------------------------------------

def test_dp_host_split_rules():
    import jax
    devs = jax.devices()[:8]
    # single-process CPU: auto-detect finds one host -> flat
    assert pdist.dp_host_split(devs, force=0) == (1, 8)
    assert pdist.dp_host_split(devs, force=1) == (1, 8)
    # forced synthetic split (CPU simulation)
    assert pdist.dp_host_split(devs, force=2) == (2, 4)
    assert pdist.dp_host_split(devs, force=4) == (4, 2)
    with pytest.raises(MXNetError, match='not divisible'):
        pdist.dp_host_split(devs[:6], force=4)
    groups = pdist.host_topology(devs)
    assert len(groups) == 1 and len(groups[0][1]) == 8


def test_hierarchy_rejects_dp_param_specs():
    from jax.sharding import PartitionSpec as P
    net = _net()
    with pytest.raises(MXNetError, match='hierarchical dp'):
        ShardedTrainStep(net, gluon.loss.SoftmaxCrossEntropyLoss(),
                         'adamw', mesh=make_mesh((8,), ('dp',)),
                         hierarchy=2,
                         param_specs={net[0].weight.name: P('dp', None)})


# ---------------------------------------------------------------------------
# the uncompressed path is bit-unchanged; hierarchy alone is a pure
# layout change
# ---------------------------------------------------------------------------

def test_compression_off_paths_bit_identical():
    _, step_a, loss_a = _run(compression=None)
    _, step_b, loss_b = _run(compression={'type': 'none'})
    assert loss_a == loss_b
    assert step_a.compression is None and step_b.compression is None
    assert step_a.compression_report() is None
    # legacy accounting intact: zero1 reduce_scatter == all_gather bytes
    rs = step_a._comm_plan['reduce_scatter']
    ag = step_a._comm_plan['all_gather']
    assert rs[0] == ag[0] and rs[0] > 0
    assert step_a.comm_bytes_per_hop() == {'dp': int(rs[0] + ag[0])}


@pytest.mark.parametrize('H', [2, 4])
def test_hierarchy_parity_uncompressed(H):
    """Splitting dp into (cross, intra) sub-axes without compression is
    a pure layout change: the trajectory matches flat dp to <=1e-6 and
    the per-hop bytes decompose (intra param traffic + cross grad
    exchange)."""
    _, step_f, loss_f = _run(hierarchy=1)
    _, step_h, loss_h = _run(hierarchy=H)
    for a, b in zip(loss_f, loss_h):
        assert abs(a - b) <= 1e-6, (H, loss_f, loss_h)
    hops = step_h.comm_bytes_per_hop()
    assert set(hops) == {'dph', 'dpi'}
    assert hops['dph'] > 0 and hops['dpi'] > 0
    # ZeRO shard degree is the INTRA extent: states replicate across
    # host groups, so one device holds ~1/h (not 1/dp) of the state
    h = 8 // H
    _, step_flat_off, _ = _run(zero=0)
    rb = step_flat_off.opt_state_bytes_per_device()
    zb = step_h.opt_state_bytes_per_device()
    assert zb <= rb / h * 1.3 + 4096, (zb, rb, h)
    assert step_h._shard_size == h and step_h._cross_size == H
    assert tuple(step_h.mesh.axis_names) == ('dph', 'dpi')


# ---------------------------------------------------------------------------
# error-feedback compression in the compiled step
# ---------------------------------------------------------------------------

def test_fp16_compression_close_to_uncompressed():
    """fp16 EF truncation at lr=0.01 over 3 steps stays within a tight
    bound of the uncompressed trajectory, with the residual carried as
    SHARDED per-param fp32 state."""
    _, step_u, loss_u = _run()
    _, step_c, loss_c = _run(compression={'type': 'fp16'})
    for a, b in zip(loss_u, loss_c):
        assert abs(a - b) <= 5e-5, (loss_u, loss_c)
    rep = step_c.compression_report()
    assert rep['codec'] == 'fp16' and rep['ratio'] == 2.0
    assert rep['residual_bytes_per_device'] > 0
    # residuals shard with the grad layout (zero1: 1/dp per device)
    for n, r in step_c._residual.items():
        assert tuple(r.shape) == tuple(step_c._residual_shapes[n])
        if step_c.zero_specs[n] is not None:
            assert not r.sharding.is_fully_replicated, n


def test_2bit_compression_trains_and_is_deterministic():
    _, step_a, loss_a = _run(compression={'type': '2bit'}, steps=10)
    _, step_b, loss_b = _run(compression={'type': '2bit'}, steps=10)
    assert loss_a == loss_b          # same seed -> bit-identical
    assert all(onp.isfinite(l) for l in loss_a)
    assert loss_a[-1] < loss_a[0]    # still learns through the codec
    # the residual is genuinely nonzero (error is being carried)
    total = sum(float(onp.sum(onp.abs(onp.asarray(r))))
                for r in step_a._residual.values())
    assert total > 0


def test_hier_cross_hop_shrink_ratios():
    """The acceptance ratios: the cross-host gradient exchange carries
    the encoded payload — >=3x smaller for 2bit (and int8), >=1.9x for
    fp16 — while the intra hop stays full precision."""
    _, base, _ = _run(hierarchy=2, steps=1)
    before = base.comm_bytes_per_hop()
    for ctype, floor in (('2bit', 3.0), ('int8', 3.0), ('fp16', 1.9)):
        _, step, _ = _run(compression={'type': ctype}, hierarchy=2,
                          steps=1)
        after = step.comm_bytes_per_hop()
        assert after['dpi'] == before['dpi'], ctype   # ICI untouched
        shrink = before['dph'] / max(1, after['dph'])
        assert shrink >= floor, (ctype, before, after, shrink)
        rep = step.compression_report()
        assert rep['axis'] == 'dph'
        assert rep['ratio'] >= floor, (ctype, rep)


def test_zero_stages_compose_with_compression():
    """Compression fixed, ZeRO stage varied: the quantization epilogue
    sees the same mathematical gradient either way, so zero3 matches
    zero1 to <=1e-6 (the established reduction-reorder bound)."""
    _, s1, loss_1 = _run(compression={'type': 'fp16'}, zero=1)
    _, s3, loss_3 = _run(compression={'type': 'fp16'}, zero=3)
    for a, b in zip(loss_1, loss_3):
        assert abs(a - b) <= 1e-6, (loss_1, loss_3)
    # zero3 flat params carry flat padded residuals
    for n, fz in s3._flat_meta.items():
        assert s3._residual_shapes[n] == (fz['padded'],)


def test_guard_composes_with_compression():
    """An injected NaN step under 2bit compression: the codec must NOT
    silently quantize the NaN away — the guard (which reduces over the
    DECODED grads) skips the step on device and the gated residual
    writeback keeps the error state clean."""
    mesh = make_mesh((8,), ('dp',))
    rng = onp.random.RandomState(0)
    x = nd.array(rng.randn(32, 6).astype(onp.float32))
    y = nd.array(rng.randn(32, 1).astype(onp.float32))
    net = nn.Dense(1, in_units=6)
    net.initialize()
    guard = NonFiniteGuard(policy='skip', max_consecutive_bad=10)
    step = ShardedTrainStep(net, gluon.loss.L2Loss(), 'adam',
                            {'learning_rate': 0.05}, mesh=mesh,
                            guard=guard,
                            compression_params={'type': '2bit'})
    faults.arm('step.dispatch', 'nan', window=(3, 4))
    weights = []
    try:
        for _ in range(6):
            step(x, y)
            weights.append(net.weight.data().asnumpy().copy())
    finally:
        faults.disarm()
    assert all(onp.isfinite(w).all() for w in weights)
    assert onp.array_equal(weights[2], weights[3])   # poisoned: no-op
    assert not onp.array_equal(weights[4], weights[5])
    assert guard.bad_steps == 2
    # the residual survived the poisoned steps finite
    for n, r in step._residual.items():
        assert onp.all(onp.isfinite(onp.asarray(r))), n


# ---------------------------------------------------------------------------
# checkpoint round-trips of the residual state (ISSUE 12 satellite)
# ---------------------------------------------------------------------------

def test_residuals_ride_states_payload_dp8_to_dp4(tmp_path):
    """Save under 2bit compression at dp=8, restore at dp=4 (same
    codec): the residuals re-scatter from the layout-independent
    payload and the continued trajectory matches the saving instance's
    to <=1e-6 (the established cross-dp-degree parity bound — the batch
    reduction order changes with the mesh)."""
    from mxnet_tpu.checkpoint import CheckpointManager
    net = _net()
    loss_fn = gluon.loss.SoftmaxCrossEntropyLoss()
    x, y = _data()
    comp = {'type': '2bit', 'threshold': 0.5}
    step8 = ShardedTrainStep(net, loss_fn, 'adamw',
                             {'learning_rate': 0.01},
                             mesh=make_mesh((8,), ('dp',)),
                             compression_params=comp)
    for _ in range(3):
        step8(x, y)
    blob = step8.get_states_bytes()
    doc = pickle.loads(blob)
    assert set(doc['residual']) == set(n for n, _ in step8._trainable)
    assert doc['compression']['type'] == '2bit'
    # manifest audit trail
    mgr = CheckpointManager(str(tmp_path), params=net, trainer=step8,
                            async_save=False)
    mgr.save(3)
    mgr.close()
    from mxnet_tpu.checkpoint import manifest as mf
    layout = mf.read_manifest(mgr.step_dir(3))['metadata'][
        'optimizer_state_layout']
    assert layout['compression']['type'] == '2bit'
    params_at_3 = {n: p.data().asnumpy().copy()
                   for n, p in net.collect_params().items()}
    # reference: two more steps on the saving instance
    ref_losses = [float(step8(x, y).asscalar()) for _ in range(2)]
    # restore into dp=4 with the same codec; rewind the params too
    for n, p in net.collect_params().items():
        p.set_data(nd.array(params_at_3[n]))
    step4 = ShardedTrainStep(net, loss_fn, 'adamw',
                             {'learning_rate': 0.01},
                             mesh=make_mesh((4,), ('dp',)),
                             compression_params=comp)
    step4.set_states_bytes(blob)
    got_losses = [float(step4(x, y).asscalar()) for _ in range(2)]
    for a, b in zip(got_losses, ref_losses):
        assert abs(a - b) <= 1e-6, (got_losses, ref_losses)
    # and the restored residuals round-trip bit-identically
    got = pickle.loads(step4.get_states_bytes())
    for n in doc['residual']:
        a = onp.asarray(doc['residual'][n])
        b = onp.asarray(got['residual'][n])
        assert a.shape == b.shape


def test_residual_restore_compression_off_and_reseed(tmp_path):
    """The cross-config matrix: a compressed payload restores into an
    UNCOMPRESSED step (residuals dropped — no error state to carry),
    and an uncompressed payload restores into a compressed step
    (residuals deterministically reseed to zero)."""
    net = _net()
    loss_fn = gluon.loss.SoftmaxCrossEntropyLoss()
    x, y = _data()
    comp = {'type': 'fp16'}
    step_c = ShardedTrainStep(net, loss_fn, 'adamw',
                              {'learning_rate': 0.01},
                              mesh=make_mesh((8,), ('dp',)),
                              compression_params=comp)
    for _ in range(2):
        step_c(x, y)
    blob_c = step_c.get_states_bytes()
    # compressed payload -> uncompressed step: runs, residuals dropped
    step_u = ShardedTrainStep(net, loss_fn, 'adamw',
                              {'learning_rate': 0.01},
                              mesh=make_mesh((4,), ('dp',)))
    step_u.set_states_bytes(blob_c)
    step_u(x, y)
    assert 'residual' not in pickle.loads(step_u.get_states_bytes())
    # uncompressed payload -> compressed step: zero reseed
    blob_u = step_u.get_states_bytes()
    step_c2 = ShardedTrainStep(net, loss_fn, 'adamw',
                               {'learning_rate': 0.01},
                               mesh=make_mesh((4,), ('dp',)),
                               compression_params=comp)
    step_c2(x, y)            # build + accumulate a nonzero residual
    step_c2.set_states_bytes(blob_u)
    for n, r in step_c2._residual.items():
        assert not onp.any(onp.asarray(r)), \
            f"residual {n} not reseeded to zero"


# ---------------------------------------------------------------------------
# telemetry contract
# ---------------------------------------------------------------------------

def test_compression_telemetry_contract():
    was_on = telemetry.enabled()
    telemetry.enable()
    try:
        telemetry.reset()
        _, step, _ = _run(compression={'type': '2bit'}, hierarchy=2,
                          steps=2)
        rep = step.compression_report()
        enc_step = step._comp_plan['encoded_bytes']   # unrounded
        enc = telemetry.value('mxnet_tpu_comm_compressed_bytes_total',
                              codec='2bit', axis='dph')
        assert enc == pytest.approx(2 * enc_step, rel=1e-6)
        assert telemetry.value('mxnet_tpu_comm_compression_ratio') == \
            pytest.approx(rep['ratio'])
        assert telemetry.value(
            'mxnet_tpu_comm_residual_bytes_per_device') == \
            step.residual_bytes_per_device()
        # per-hop collective bytes: the cross hop carries the ENCODED
        # size under kind=all_reduce/axis=dph
        cross = telemetry.value('mxnet_tpu_comm_collective_bytes_total',
                                kind='all_reduce', axis='dph',
                                stage='zero1')
        assert cross == pytest.approx(2 * enc_step, rel=1e-6)
        intra_rs = telemetry.value(
            'mxnet_tpu_comm_collective_bytes_total',
            kind='reduce_scatter', axis='dpi', stage='zero1')
        assert intra_rs and intra_rs > cross
    finally:
        if not was_on:
            telemetry.disable()


# ---------------------------------------------------------------------------
# gluon.Trainer runs unmodified with compression_params
# ---------------------------------------------------------------------------

def test_trainer_with_compression_on_mesh_weights():
    import jax
    from jax.sharding import NamedSharding, PartitionSpec as P
    mesh = make_mesh((8,), ('dp',))
    net = _net()
    x, y = _data()
    net(x)
    repl = NamedSharding(mesh, P())
    for p in net.collect_params().values():
        p.data()._data = jax.device_put(p.data()._data, repl)
    x._data = jax.device_put(x._data, repl)
    y._data = jax.device_put(y._data, repl)
    loss_fn = gluon.loss.SoftmaxCrossEntropyLoss()
    trainer = gluon.Trainer(net.collect_params(), 'sgd',
                            {'learning_rate': 0.01},
                            compression_params={'type': '2bit'})
    before = {n: p.data().asnumpy().copy()
              for n, p in net.collect_params().items()}
    for _ in range(2):
        with autograd.record():
            loss = loss_fn(net(x), y)
        loss.backward()
        trainer.step(x.shape[0])
    after = {n: p.data().asnumpy() for n, p in net.collect_params().items()}
    assert any(not onp.array_equal(before[n], after[n]) for n in before)
    assert all(onp.isfinite(v).all() for v in after.values())
    # a states restore resets the carried residuals (deterministic)
    comp = trainer._kvstore._compression or trainer._local_compression()
    blob = trainer.get_states_bytes()
    trainer.set_states_bytes(blob)
    assert not comp._residual


def test_module_routes_compression_params():
    """The Module API's long-ignored ``compression_params`` now routes
    to the shared codecs (applied to the summed gradient in update() —
    the same contract as the Trainer's no-push paths)."""
    from mxnet_tpu import symbol as sym
    from mxnet_tpu.module import Module
    from mxnet_tpu.io import NDArrayIter
    rng = onp.random.RandomState(0)
    X = rng.randn(32, 6).astype('float32')
    Y = (X.sum(1) > 0).astype('float32')
    x = sym.Variable('data')
    w = sym.Variable('fc_weight', shape=(2, 6))
    b = sym.Variable('fc_bias', shape=(2,))
    out = sym.SoftmaxOutput(
        sym.FullyConnected(x, w, b, num_hidden=2, name='fc'),
        sym.Variable('softmax_label'), name='softmax')
    mod = Module(out, data_names=('data',),
                 label_names=('softmax_label',), context=mx.cpu(0),
                 compression_params={'type': '2bit', 'threshold': 0.1})
    it = NDArrayIter(X, Y, batch_size=16, label_name='softmax_label')
    mod.fit(it, num_epoch=1, optimizer_params=(('learning_rate', 0.1),))
    assert mod._compression is not None and mod._compression._residual
    with pytest.raises(MXNetError, match='not supported'):
        Module(out, data_names=('data',), label_names=('softmax_label',),
               compression_params={'type': 'bogus'})


def test_compression_determinism_3x():
    """Drives tools/flakiness_checker.py over the compression
    determinism test 3x (distinct MXNET_TEST_SEED per trial): the codec
    epilogue is a pure function of the trajectory, so every trial must
    pass."""
    tools = os.path.join(os.path.dirname(__file__), os.pardir, 'tools',
                         'flakiness_checker.py')
    res = subprocess.run(
        [sys.executable, tools,
         'tests/test_compression.py::'
         'test_2bit_compression_trains_and_is_deterministic',
         '-n', '3'],
        cwd=os.path.join(os.path.dirname(__file__), os.pardir),
        capture_output=True, text=True, timeout=600)
    assert res.returncode == 0, res.stdout + res.stderr
    assert '3/3 passed' in res.stdout
