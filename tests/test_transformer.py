"""Transformer enc-dec model (models/transformer.py; ref: the WMT
transformer verification config + src/operator/contrib/transformer.cc
attention kernels)."""
import numpy as onp
import pytest

import mxnet_tpu as mx
from mxnet_tpu import nd
from mxnet_tpu.models import TransformerModel, TransformerEncoder


def _tiny(vocab=32):
    return TransformerModel(vocab, vocab, hidden=32, enc_layers=1,
                            dec_layers=1, heads=2, ffn_hidden=64,
                            max_len=64, dropout=0.0)


def test_transformer_shapes():
    net = _tiny()
    net.initialize(mx.init.Xavier())
    src = nd.array(onp.random.RandomState(0).randint(0, 32, (2, 10))
                   .astype('int32'))
    tgt = nd.array(onp.random.RandomState(1).randint(0, 32, (2, 7))
                   .astype('int32'))
    out = net(src, tgt)
    assert out.shape == (2, 7, 32)


def test_decoder_is_causal():
    """Changing a future decoder-input token must not change earlier
    positions' logits (the decoder self-attention is causal — this path
    was previously untested and carried a dead `causal` kwarg)."""
    net = _tiny()
    net.initialize(mx.init.Xavier())
    rng = onp.random.RandomState(0)
    src = nd.array(rng.randint(0, 32, (1, 8)).astype('int32'))
    tgt = rng.randint(0, 32, (1, 6)).astype('int32')
    out1 = net(src, nd.array(tgt)).asnumpy()
    tgt2 = tgt.copy()
    tgt2[0, 4] = (tgt2[0, 4] + 1) % 32     # perturb position 4
    out2 = net(src, nd.array(tgt2)).asnumpy()
    # positions 0..3 unchanged; position >= 4 changed
    onp.testing.assert_allclose(out1[0, :4], out2[0, :4],
                                rtol=1e-5, atol=1e-6)
    assert onp.abs(out1[0, 4:] - out2[0, 4:]).max() > 1e-4


def test_encoder_mask_drops_padding():
    net = TransformerEncoder(32, hidden=32, layers=1, heads=2,
                             ffn_hidden=64, max_len=64, dropout=0.0)
    net.initialize(mx.init.Xavier())
    rng = onp.random.RandomState(0)
    src = rng.randint(0, 32, (2, 8)).astype('int32')
    import jax.numpy as jnp
    vlen = jnp.asarray([5, 8])
    mask = (jnp.arange(8)[None, None, None, :] <
            vlen[:, None, None, None])
    out_m = net(nd.array(src), nd.array(mask)).asnumpy()
    # perturb a PADDED source token for row 0: masked output unchanged
    src2 = src.copy()
    src2[0, 6] = (src2[0, 6] + 3) % 32
    out_m2 = net(nd.array(src2), nd.array(mask)).asnumpy()
    onp.testing.assert_allclose(out_m[0, :5], out_m2[0, :5],
                                rtol=1e-5, atol=1e-6)


def test_transformer_training_reduces_loss():
    from mxnet_tpu.models.bert import masked_cross_entropy
    from mxnet_tpu.parallel import make_mesh, ShardedTrainStep
    import jax
    net = _tiny(vocab=16)
    net.initialize(mx.init.Xavier())
    mesh = make_mesh((1,), ('dp',), devices=jax.devices()[:1])
    step = ShardedTrainStep(net, masked_cross_entropy, 'adam',
                            {'learning_rate': 1e-3}, mesh=mesh)
    rng = onp.random.RandomState(0)
    src = rng.randint(4, 16, (8, 6)).astype('int32')
    tgt_out = src[:, ::-1].copy()
    tgt_in = onp.concatenate(
        [onp.ones((8, 1), onp.int32), tgt_out[:, :-1]], axis=1)
    losses = []
    for _ in range(12):
        losses.append(float(step([nd.array(src), nd.array(tgt_in)],
                                 [nd.array(tgt_out)]).asnumpy()))
    assert onp.isfinite(losses).all()
    assert losses[-1] < losses[0], losses
