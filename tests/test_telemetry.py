"""Telemetry subsystem: registry semantics, exports, hot-path
instrumentation, recompile detector, disabled-mode fast path
(ISSUE 1 tentpole; ref for the shape: src/profiler/profiler.h — one sink
every layer reports into)."""
import json
import logging
import os
import subprocess
import sys
import time
import types
import warnings

import numpy as onp
import pytest

import mxnet_tpu as mx
from mxnet_tpu import autograd, gluon, io, nd, telemetry


@pytest.fixture()
def telem():
    """Clean, enabled registry; disabled and cleaned again afterwards."""
    telemetry.reset()
    telemetry.enable()
    yield telemetry
    telemetry.reset()
    telemetry.disable()
    telemetry.set_recompile_threshold(None)
    telemetry.set_step_flops(None, None)


# ---------------------------------------------------------------------------
# registry semantics
# ---------------------------------------------------------------------------

def test_counter_gauge_histogram_semantics(telem):
    c = telemetry.counter('mxnet_tpu_test_requests_total')
    c.inc()
    c.inc(4)
    c.inc(2, route='a')
    assert c.value() == 5
    assert c.value(route='a') == 2
    assert c.value(route='missing') is None

    g = telemetry.gauge('mxnet_tpu_test_temperature')
    g.set(1.5)
    g.set(2.5)
    assert g.value() == 2.5

    h = telemetry.histogram('mxnet_tpu_test_latency_seconds',
                            buckets=(1.0, 10.0))
    for v in (0.5, 5.0, 50.0):
        h.observe(v)
    count, total = h.value()
    assert count == 3 and total == 55.5

    # get-or-create returns the same object; kind mismatch is an error
    assert telemetry.counter('mxnet_tpu_test_requests_total') is c
    with pytest.raises(mx.MXNetError):
        telemetry.gauge('mxnet_tpu_test_requests_total')


def test_metric_name_validation(telem):
    for bad in ('requests_total', 'mxnet_tpu_CamelCase', 'mxnet_tpu_'):
        with pytest.raises(mx.MXNetError):
            telemetry.counter(bad)


def test_reset_zeroes_values(telem):
    telemetry.inc('mxnet_tpu_test_requests_total', 7)
    telemetry.set_gauge('mxnet_tpu_test_temperature', 3.0)
    telemetry.observe('mxnet_tpu_test_latency_seconds', 0.1)
    assert telemetry.report() != ''
    telemetry.reset()
    assert telemetry.value('mxnet_tpu_test_requests_total') is None
    assert telemetry.value('mxnet_tpu_test_latency_seconds') is None
    assert telemetry.report() == ''


# ---------------------------------------------------------------------------
# exports: Prometheus / JSON / chrome-trace
# ---------------------------------------------------------------------------

def test_prometheus_golden(telem):
    telemetry.counter('mxnet_tpu_test_golden_requests_total',
                      help='requests').inc(3, route='a')
    telemetry.set_gauge('mxnet_tpu_test_golden_temperature', 1.5)
    h = telemetry.histogram('mxnet_tpu_test_golden_latency_seconds',
                            buckets=(1.0, 10.0))
    for v in (0.5, 5.0, 50.0):
        h.observe(v)
    expected = (
        '# TYPE mxnet_tpu_test_golden_latency_seconds histogram\n'
        'mxnet_tpu_test_golden_latency_seconds_bucket{le="1.0"} 1\n'
        'mxnet_tpu_test_golden_latency_seconds_bucket{le="10.0"} 2\n'
        'mxnet_tpu_test_golden_latency_seconds_bucket{le="+Inf"} 3\n'
        'mxnet_tpu_test_golden_latency_seconds_sum 55.5\n'
        'mxnet_tpu_test_golden_latency_seconds_count 3\n'
        '# HELP mxnet_tpu_test_golden_requests_total requests\n'
        '# TYPE mxnet_tpu_test_golden_requests_total counter\n'
        'mxnet_tpu_test_golden_requests_total{route="a"} 3\n'
        '# TYPE mxnet_tpu_test_golden_temperature gauge\n'
        'mxnet_tpu_test_golden_temperature 1.5\n'
    )
    assert telemetry.prometheus() == expected


def test_json_dump_golden(telem, tmp_path):
    telemetry.counter('mxnet_tpu_test_golden_requests_total',
                      help='requests').inc(3, route='a')
    h = telemetry.histogram('mxnet_tpu_test_golden_latency_seconds',
                            buckets=(1.0, 10.0))
    h.observe(0.5)
    path = telemetry.dump(str(tmp_path / 'telemetry.json'))
    doc = json.load(open(path))
    assert doc['mxnet_tpu_test_golden_requests_total'] == {
        'type': 'counter', 'help': 'requests',
        'series': [{'labels': {'route': 'a'}, 'value': 3}]}
    hist = doc['mxnet_tpu_test_golden_latency_seconds']
    assert hist['type'] == 'histogram'
    (series,) = hist['series']
    assert series['count'] == 1 and series['sum'] == 0.5
    assert series['buckets'] == {'1.0': 1, '10.0': 0, '+Inf': 0}


def test_prometheus_label_escaping(telem):
    telemetry.inc('mxnet_tpu_test_escapes_total',
                  key='he said "hi"\nback\\slash')
    out = telemetry.prometheus()
    assert (r'mxnet_tpu_test_escapes_total'
            r'{key="he said \"hi\"\nback\\slash"} 1') in out
    # no literal newline may survive inside a sample line
    assert all(line.count('"') % 2 == 0 or line.startswith('#')
               for line in out.splitlines())


def test_set_step_flops_clear_semantics(telem):
    telemetry.set_step_flops(1e9, peak_flops=1e12)
    telemetry.set_step_flops(2e9)            # omitted: peak kept
    telemetry.record_step(0.01, 1)
    assert telemetry.value('mxnet_tpu_mfu_percent') == pytest.approx(20.0)
    telemetry.set_step_flops(2e9, peak_flops=None)   # explicit: cleared
    telemetry.set_gauge('mxnet_tpu_mfu_percent', -1.0)
    telemetry.record_step(0.01, 1)
    assert telemetry.value('mxnet_tpu_mfu_percent') == -1.0  # not updated


def test_chrome_counter_events_merge_into_profiler(telem, tmp_path):
    from mxnet_tpu import profiler
    telemetry.inc('mxnet_tpu_test_requests_total', 5)
    telemetry.set_gauge('mxnet_tpu_test_temperature', 2.0)
    fname = str(tmp_path / 'trace.json')
    profiler.set_config(filename=fname)
    profiler.start()
    profiler.stop()
    profiler.dump()
    evs = json.load(open(fname))['traceEvents']
    tel = [e for e in evs if e.get('cat') == 'telemetry']
    assert all(e['ph'] == 'C' for e in tel)
    names = {e['name'] for e in tel}
    assert 'mxnet_tpu_test_requests_total' in names
    assert 'mxnet_tpu_test_temperature' in names
    # and in the dumps() JSON stream too
    evs2 = json.loads(profiler.dumps(format='json'))['traceEvents']
    assert any(e.get('cat') == 'telemetry' for e in evs2)
    profiler.set_config(filename='profile.json')


# ---------------------------------------------------------------------------
# recompile detector
# ---------------------------------------------------------------------------

def test_recompile_detector_warns_exactly_once(telem):
    telemetry.set_recompile_threshold(2)
    net = gluon.nn.Dense(2)
    net.initialize()
    net.hybridize()
    with warnings.catch_warnings(record=True) as w:
        warnings.simplefilter('always')
        for i in range(1, 7):        # 6 distinct batch shapes -> 6 compiles
            net(nd.ones((i, 4)))
    rec = [x for x in w if issubclass(x.category, telemetry.RecompileWarning)]
    assert len(rec) == 1
    msg = str(rec[0].message)
    assert f'cachedop:{net.name}' in msg and 'float32' in msg
    site = f'cachedop:{net.name}'
    assert telemetry.value('mxnet_tpu_compile_total', site=site) == 6
    assert telemetry.value('mxnet_tpu_recompile_warnings_total',
                           site=site) == 1
    # stable shapes from here on: cache hits, no further compiles
    net(nd.ones((3, 4)))
    assert telemetry.value('mxnet_tpu_compile_total', site=site) == 6
    assert telemetry.value('mxnet_tpu_compile_cache_hits_total',
                           site=site) >= 1


def test_compile_seconds_counter(telem):
    net = gluon.nn.Dense(2)
    net.initialize()
    net.hybridize()
    net(nd.ones((2, 3)))
    site = f'cachedop:{net.name}'
    assert telemetry.value('mxnet_tpu_compile_seconds_total', site=site) > 0


# ---------------------------------------------------------------------------
# step metrics / MFU
# ---------------------------------------------------------------------------

def test_record_step_and_mfu_gauge(telem):
    telemetry.set_step_flops(1e9, peak_flops=1e12)
    telemetry.record_step(0.01, 32)
    count, total = telemetry.value('mxnet_tpu_step_time_seconds')
    assert count == 1 and total == pytest.approx(0.01)
    assert telemetry.value('mxnet_tpu_samples_per_second') == \
        pytest.approx(3200.0)
    # 1e9 FLOPs in 10ms against a 1e12 FLOP/s peak = 10% MFU
    assert telemetry.value('mxnet_tpu_mfu_percent') == pytest.approx(10.0)


def test_speedometer_pulls_gauge_and_counts(telem, caplog):
    # a just-recorded step marks the gauge fresh
    telemetry.record_step(0.1, 123.45)     # -> 1234.5 samples/sec
    sp = mx.callback.Speedometer(batch_size=8, frequent=1)
    sp(types.SimpleNamespace(nbatch=0, epoch=0, eval_metric=None))
    with caplog.at_level(logging.INFO):
        sp(types.SimpleNamespace(nbatch=1, epoch=0, eval_metric=None))
    assert '1234.50' in caplog.text
    assert telemetry.value('mxnet_tpu_speedometer_logs_total') == 1


def test_speedometer_ignores_stale_gauge(telem, caplog):
    # gauge set long "ago" (no record_step timestamp): must recompute
    telemetry.set_gauge('mxnet_tpu_samples_per_second', 99999.0)
    sp = mx.callback.Speedometer(batch_size=8, frequent=1)
    sp(types.SimpleNamespace(nbatch=0, epoch=0, eval_metric=None))
    with caplog.at_level(logging.INFO):
        sp(types.SimpleNamespace(nbatch=1, epoch=0, eval_metric=None))
    assert '99999' not in caplog.text
    assert 'samples/sec' in caplog.text


def test_speedometer_recomputes_without_gauge(telem, caplog):
    sp = mx.callback.Speedometer(batch_size=8, frequent=1)
    sp(types.SimpleNamespace(nbatch=0, epoch=0, eval_metric=None))
    with caplog.at_level(logging.INFO):
        sp(types.SimpleNamespace(nbatch=1, epoch=0, eval_metric=None))
    assert 'samples/sec' in caplog.text
    assert telemetry.value('mxnet_tpu_speedometer_logs_total') == 1


def test_trainer_step_pause_guard(telem):
    """A long gap between step() calls (eval pass, checkpoint) must not
    land in the step-time histogram."""
    net = gluon.nn.Dense(1)
    net.initialize()
    trainer = gluon.Trainer(net.collect_params(), 'sgd',
                            {'learning_rate': 0.0}, kvstore=None)
    x = nd.ones((2, 3))

    def one_step():
        with autograd.record():
            loss = net(x).sum()
        loss.backward()
        trainer.step(2)

    one_step()     # first step: no previous timestamp, nothing recorded
    assert telemetry.value('mxnet_tpu_step_time_seconds') is None
    # simulate a 10s pause against a 0.1s running step time: skipped
    trainer._telem_step_ema = 0.1
    trainer._telem_last_step = time.perf_counter() - 10.0
    one_step()
    assert telemetry.value('mxnet_tpu_step_time_seconds') is None
    # a normal-length interval is recorded
    trainer._telem_last_step = time.perf_counter() - 0.005
    one_step()
    count, total = telemetry.value('mxnet_tpu_step_time_seconds')
    assert count == 1 and total < 2.0
    trainer.reset_step_timer()
    assert trainer._telem_last_step is None


# ---------------------------------------------------------------------------
# IO instrumentation
# ---------------------------------------------------------------------------

def test_io_batch_latency_histogram(telem):
    X = onp.arange(32, dtype=onp.float32).reshape(16, 2)
    it = io.NDArrayIter(X, None, batch_size=4)
    batches = list(it)
    assert len(batches) == 4
    count, _ = telemetry.value('mxnet_tpu_io_batch_latency_seconds')
    assert count == 4
    assert telemetry.value('mxnet_tpu_io_batches_total') == 4


def test_prefetch_miss_and_stall_counters(telem):
    class SlowIter(io.DataIter):
        def __init__(self):
            super().__init__(batch_size=1)
            self.n = 0

        def next(self):
            if self.n >= 2:
                raise StopIteration
            self.n += 1
            time.sleep(0.05)
            return io.DataBatch(data=[nd.ones((1, 2))])

    pf = io.PrefetchingIter(SlowIter())
    got = 0
    while True:
        try:
            pf.next()
            got += 1
        except StopIteration:
            break
    assert got == 2
    # the producer sleeps before the first put: the consumer must have
    # stalled at least once, and the stall time was accounted
    assert telemetry.value('mxnet_tpu_io_prefetch_miss_total') >= 1
    assert telemetry.value(
        'mxnet_tpu_io_prefetch_stall_seconds_total') > 0


# ---------------------------------------------------------------------------
# executor instrumentation
# ---------------------------------------------------------------------------

def test_executor_forward_metrics(telem):
    a = mx.sym.var('a')
    b = a * 2
    exe = b.simple_bind(ctx=mx.cpu(), a=(2, 2))
    exe.forward(is_train=False, a=nd.ones((2, 2)))
    exe.forward(is_train=False, a=nd.ones((2, 2)))
    assert telemetry.value('mxnet_tpu_executor_forward_total') == 2
    count, _ = telemetry.value('mxnet_tpu_executor_forward_seconds')
    assert count == 2


# ---------------------------------------------------------------------------
# end-to-end acceptance: a small Trainer loop fills every hot-path metric
# ---------------------------------------------------------------------------

def test_training_loop_populates_report(telem):
    rng = onp.random.RandomState(0)
    X = rng.rand(32, 8).astype(onp.float32)
    Y = rng.rand(32, 1).astype(onp.float32)
    it = io.NDArrayIter(X, Y, batch_size=8)

    net = gluon.nn.Dense(1)
    net.initialize()
    net.hybridize()
    trainer = gluon.Trainer(net.collect_params(), 'sgd',
                            {'learning_rate': 0.01},
                            update_on_kvstore=True)
    for batch in it:
        x, y = batch.data[0], batch.label[0]
        with autograd.record():
            loss = ((net(x) - y) ** 2).mean()
        loss.backward()
        trainer.step(8)

    # op dispatch
    assert telemetry.value('mxnet_tpu_imperative_ops_total') > 0
    # compile cache
    site = f'cachedop:{net.name}'
    assert telemetry.value('mxnet_tpu_compile_total', site=site) >= 1
    # kvstore bytes (update_on_kvstore pushes grads / pulls weights)
    assert telemetry.value('mxnet_tpu_kvstore_push_bytes_total',
                           key='0') > 0
    assert telemetry.value('mxnet_tpu_kvstore_pull_bytes_total',
                           key='0') > 0
    # IO histogram
    io_count, _ = telemetry.value('mxnet_tpu_io_batch_latency_seconds')
    assert io_count == 4
    # step-time histogram: 4 steps -> 3 inter-step intervals, the first
    # of which only seeds the pause/compile filter and is not recorded
    step_count, _ = telemetry.value('mxnet_tpu_step_time_seconds')
    assert step_count == 2
    assert telemetry.value('mxnet_tpu_samples_per_second') > 0

    rep = telemetry.report()
    for needle in ('mxnet_tpu_imperative_ops_total',
                   'mxnet_tpu_compile_total',
                   'mxnet_tpu_kvstore_push_bytes_total',
                   'mxnet_tpu_io_batch_latency_seconds',
                   'mxnet_tpu_step_time_seconds'):
        assert needle in rep, f"report missing {needle}"


# ---------------------------------------------------------------------------
# disabled mode
# ---------------------------------------------------------------------------

def test_disabled_leaves_zero_counters():
    telemetry.reset()
    telemetry.disable()
    a = nd.ones((2, 2))
    (a * 2).wait_to_read()
    net = gluon.nn.Dense(2)
    net.initialize()
    net.hybridize()
    net(nd.ones((1, 4)))
    X = onp.zeros((4, 2), onp.float32)
    list(io.NDArrayIter(X, None, batch_size=2))
    assert telemetry.value('mxnet_tpu_imperative_ops_total') is None
    assert telemetry.value('mxnet_tpu_io_batches_total') is None
    assert telemetry.report() == ''
    assert telemetry.prometheus() == ''
    assert not telemetry.enabled()


def test_env_gate_declared():
    assert 'MXNET_TPU_TELEMETRY' in mx.config.list_vars()
    assert 'MXNET_TPU_RECOMPILE_WARN_THRESHOLD' in mx.config.list_vars()
    assert mx.config.get('MXNET_TPU_RECOMPILE_WARN_THRESHOLD') >= 1


# ---------------------------------------------------------------------------
# CI lint: metric names unique, lowercase_snake, namespaced
# ---------------------------------------------------------------------------

def test_metric_name_lint():
    tool = os.path.join(os.path.dirname(__file__), os.pardir,
                        'tools', 'check_telemetry_names.py')
    res = subprocess.run([sys.executable, tool], capture_output=True,
                         text=True)
    assert res.returncode == 0, res.stderr
