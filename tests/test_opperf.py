"""Per-op benchmark harness (benchmark/opperf.py; ref: benchmark/opperf/
suite publishing fwd/bwd latency tables)."""
import os
import sys

import pytest


def test_opperf_smoke(tmp_path):
    sys.path.insert(0, os.path.join(os.path.dirname(__file__), os.pardir,
                                    'benchmark'))
    import opperf
    fwd, bwd = opperf.bench_op('relu', [__import__('numpy').ones(
        (64, 64), 'float32')], {}, iters=2, warmup=1)
    assert fwd > 0
    assert bwd is not None and bwd > 0


def test_opperf_profiles_resolve():
    """Every profiled op exists in the registry (guards against op
    renames silently breaking the published table)."""
    sys.path.insert(0, os.path.join(os.path.dirname(__file__), os.pardir,
                                    'benchmark'))
    import opperf
    import mxnet_tpu as mx
    ops = set(mx.list_ops())
    missing = [n for n in opperf.default_profiles() if n not in ops]
    assert not missing, missing
