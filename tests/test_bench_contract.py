"""bench.py driver-artifact JSON contract (ISSUE 12 satellite — the
BENCH_r05 leak): tunnel state rides ONLY in the "probe" field, earlier
measurement-attempt failures in "attempts_failed", and top-level
"error" appears exclusively on the no-metric-at-all fallback line.

The parent orchestration is driven with a stubbed ``_run_child`` so no
subprocess (and no jax backend) is touched — these are contract tests
on the emitted JSON line, not benchmarks."""
import importlib.util
import io
import json
import os
import sys

import pytest


@pytest.fixture()
def bench(monkeypatch):
    path = os.path.join(os.path.dirname(__file__), os.pardir, 'bench.py')
    spec = importlib.util.spec_from_file_location('bench_under_test', path)
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    monkeypatch.setattr(mod.time, 'sleep', lambda *_a: None)
    monkeypatch.setattr(sys, 'argv', ['bench.py'])
    return mod


def _emitted_line(mod, capsys):
    out = capsys.readouterr().out
    lines = [l for l in out.strip().splitlines() if l.startswith('{')]
    assert lines, f"no JSON line emitted: {out!r}"
    return json.loads(lines[-1])


SMOKE = {'metric': 'bert_smoke_samples_per_sec_per_chip', 'value': 16.6,
         'unit': 'samples/sec/chip', 'vs_baseline': 0.0, 'backend': 'cpu'}


def test_wedged_probe_never_leaks_into_top_level_error(bench, capsys,
                                                       monkeypatch):
    """The BENCH_r05 regression: probe times out (wedged tunnel), the
    CPU smoke still succeeds — the valid metric line must carry the
    tunnel state in "probe" and NO top-level "error"."""
    def fake_run_child(mode, timeout):
        if mode == 'probe':
            return None, f"timeout after {timeout:.0f}s (mode=probe)"
        assert mode == 'cpu'
        return dict(SMOKE), None
    monkeypatch.setattr(bench, '_run_child', fake_run_child)
    bench.main()
    doc = _emitted_line(bench, capsys)
    assert doc['metric'] == SMOKE['metric']
    assert 'error' not in doc, doc
    assert 'attempts_failed' not in doc          # no MEASUREMENT failed
    assert doc['probe']['state'] == 'wedged'
    assert doc['probe']['attempts'] == 2         # one retry with backoff
    assert 'mode=probe' in doc['probe']['error']


def test_accel_attempt_failure_rides_attempts_failed(bench, capsys,
                                                     monkeypatch):
    """Probe sees an accelerator, the accel measurement child dies, the
    CPU smoke lands: the failure is attempt state, not an error of the
    valid smoke line."""
    def fake_run_child(mode, timeout):
        if mode == 'probe':
            return {'probe': 'ok', 'platform': 'tpu',
                    'device_kind': 'v5e', 'n_devices': 4}, None
        if mode == 'auto':
            return None, f"timeout after {timeout:.0f}s (mode=auto)"
        return dict(SMOKE), None
    monkeypatch.setattr(bench, '_run_child', fake_run_child)
    bench.main()
    doc = _emitted_line(bench, capsys)
    assert 'error' not in doc, doc
    assert doc['probe']['state'] == 'ok'
    assert doc['attempts_failed'] == ['timeout after 540s (mode=auto)']


def test_compile_report_contract(bench, monkeypatch):
    """The "compile" field (ISSUE 16): cold/warm probe children share
    one cache dir + ledger, the A/B carries warm_hit and the backend
    speedup — pinned with a stubbed probe so no subprocess (and no jax
    compile) runs."""
    calls = []

    def fake_probe(cache_dir, ledger, timeout):
        calls.append((cache_dir, ledger))
        cold = not calls[1:]
        return {
            'loss': 7.5,
            'site_seconds': {'step:train_step': 6.1 if cold else 1.4},
            'step': {'trace': 0.9, 'lower': 0.4,
                     'backend': 4.8 if cold else 0.25,
                     'total': 6.1 if cold else 1.4},
            'cache': ({'hits': 0, 'misses': 17, 'saved_seconds_est': 0.0}
                      if cold else
                      {'hits': 17, 'misses': 0,
                       'saved_seconds_est': 6.1}),
            'ledger_entries': 1 if cold else 2,
        }

    monkeypatch.setattr(bench, '_run_compile_probe', fake_probe)
    monkeypatch.delenv('BENCH_CHILD_DEADLINE', raising=False)
    rep = bench._compile_report()
    # both children must share ONE cache dir and ONE ledger file — the
    # warm process's hit and saved-seconds estimate depend on it
    assert len(calls) == 2 and calls[0] == calls[1]
    ab = rep['cache_ab']
    assert ab['warm_hit'] is True
    assert ab['backend_speedup'] == round(4.8 / 0.25, 1)
    assert ab['cold']['cache']['misses'] == 17
    assert ab['warm']['cache']['saved_seconds_est'] == 6.1
    assert 'enabled' in rep and 'ledger_path' in rep


def test_compile_report_respects_child_deadline(bench, monkeypatch):
    """Too little left on the child budget: the A/B is skipped, never
    started — the flagship metric's deadline wins."""
    def boom(*_a):
        raise AssertionError("probe must not spawn under a tight deadline")
    monkeypatch.setattr(bench, '_run_compile_probe', boom)
    monkeypatch.setenv('BENCH_CHILD_DEADLINE',
                       str(bench.time.time() + 60))
    rep = bench._compile_report()
    assert rep['cache_ab'] == {'skipped': 'child deadline too close'}


def test_serving_report_contract(bench, monkeypatch):
    """The "serving" field (ISSUE 17): a measured deadline sweep with
    QPS + p50/p99 per point, an int8 A/B with bounded output drift, and
    the fleet numbers from the (stubbed) two-replica drill — the
    in-process half runs for real on the tiny model, the subprocess
    drill is pinned."""
    import mxnet_tpu.resilience.drill as drill
    fake = {
        'ok': True, 'requests': 90, 'failed': 0, 'failovers': 2,
        'mttr_seconds': 0.21, 'reloaded_step': 7,
        'warmup': {1: {'total_seconds': 0.9, 'compiles': 19,
                       'cache': {'hits': 0, 'misses': 15}},
                   2: {'total_seconds': 0.5, 'compiles': 19,
                       'cache': {'hits': 15, 'misses': 0}}},
        'stats': {1: {'p50_ms': 5.1}, 2: {'p50_ms': 4.9}},
    }
    monkeypatch.setattr(drill, 'run_serving_drill',
                        lambda td, timeout=180.0: fake)
    monkeypatch.delenv('BENCH_CHILD_DEADLINE', raising=False)
    rep = bench._serving_report(requests=12, deadlines=(0.0, 2.0))
    assert rep['warmup']['compiles'] > 0
    sweep = rep['deadline_sweep']
    assert set(sweep) == {'0ms', '2ms'}
    for point in sweep.values():
        assert point['qps'] > 0 and not point['errors']
        assert point['p99_ms'] >= point['p50_ms']
    assert rep['int8_ab']['max_output_drift'] < 0.1
    fleet = rep['fleet']
    assert fleet['failed'] == 0 and fleet['mttr_seconds'] == 0.21
    assert fleet['warm_cache_hits'] == 15
    assert fleet['warmup_warm_seconds'] < fleet['warmup_cold_seconds']


def test_serving_report_fleet_respects_child_deadline(bench, monkeypatch):
    """Too little left on the child budget: the fleet drill is skipped,
    never spawned — the flagship metric's deadline wins (the same
    contract as the compile A/B)."""
    import mxnet_tpu.resilience.drill as drill

    def boom(*_a, **_k):
        raise AssertionError("drill must not spawn under a tight deadline")
    monkeypatch.setattr(drill, 'run_serving_drill', boom)
    monkeypatch.setenv('BENCH_CHILD_DEADLINE',
                       str(bench.time.time() + 60))
    rep = bench._serving_report(requests=8, deadlines=(2.0,))
    assert rep['fleet'] == {'skipped': 'child deadline too close'}


def test_autotune_report_contract(bench, monkeypatch, tmp_path):
    """The "autotune" field (ISSUE 18): the stubbed sweep's winner
    lands in the report AND the consumption round trip resolves a
    fresh _block_sizes call to the persisted DB winner (source db) —
    the same path the compile-ledger signature records in training."""
    import jax.numpy as jnp

    from mxnet_tpu.ops import autotune

    def fake_sweep(db_dir, heads=12, seq=512, head_dim=64):
        sig = autotune.shape_sig(heads, seq, seq, head_dim,
                                 jnp.dtype(jnp.float32), 'fwd')
        autotune.record_winner(autotune.KERNEL_FA, sig, (2, 256, 128),
                               {'source': 'measured'}, dir_=db_dir)
        return {'mode': 'measured', 'sweep_seconds': 1.2,
                'fwd': {'winner': [2, 256, 128], 'source': 'measured',
                        'candidates': 9, 'pruned': 3,
                        'signature': sig}}

    monkeypatch.setattr(bench, '_run_autotune_sweep', fake_sweep)
    monkeypatch.delenv('BENCH_CHILD_DEADLINE', raising=False)
    monkeypatch.delenv('MXTPU_AUTOTUNE_DIR', raising=False)
    autotune.clear()
    try:
        rep = bench._autotune_report()
    finally:
        autotune.clear()
    assert rep['mode'] == 'measured'
    assert rep['fwd']['winner'] == [2, 256, 128]
    assert rep['consumed']['blocks'] == [2, 256, 128]
    assert any(v.startswith('db:')
               for v in rep['consumed']['decisions'].values())
    # the temp DB dir must not leak into the process env
    import os as _os
    assert 'MXTPU_AUTOTUNE_DIR' not in _os.environ


def test_autotune_report_respects_child_deadline(bench, monkeypatch):
    """Too little left on the child budget: the sweep is skipped, never
    started — the flagship metric's deadline wins (the compile-A/B
    contract)."""
    def boom(*_a, **_k):
        raise AssertionError("sweep must not run under a tight deadline")
    monkeypatch.setattr(bench, '_run_autotune_sweep', boom)
    monkeypatch.setenv('BENCH_CHILD_DEADLINE',
                       str(bench.time.time() + 60))
    rep = bench._autotune_report()
    assert rep == {'skipped': 'child deadline too close'}


def test_sparse_report_contract(bench, monkeypatch):
    """The "sparse" field (ISSUE 19): the stubbed drill's analytic
    report and hot-fraction sweep land in the emitted field — shrink,
    per-hop exchange bytes, and one sweep row per fraction."""
    def fake_drill(*_a, **_k):
        return {
            'report': {
                'mode': 'lazy',
                'tables': {'emb0_weight': {'vocab': 20000, 'dim': 32,
                                           'budget': 512,
                                           'ids_per_step': 512}},
                'update_bytes_per_step': 512 * 32 * 4,
                'dense_update_bytes_per_step': 20000 * 32 * 4,
                'update_shrink': 39.06,
                'exchange_bytes_per_hop': {
                    'dp': {'bytes': 1024, 'dense_bytes': 40960}},
            },
            'sweep': [{'hot_fraction': 0.1, 'sparse_p50_ms': 1.0,
                       'dense_p50_ms': 3.0, 'live_rows': 400,
                       'update_bytes': 51200, 'dedup_ratio': 1.28}],
        }

    monkeypatch.setattr(bench, '_run_sparse_drill', fake_drill)
    monkeypatch.delenv('BENCH_CHILD_DEADLINE', raising=False)
    rep = bench._sparse_report()
    assert rep['mode'] == 'lazy'
    assert rep['update_shrink'] == 39.06
    assert rep['dense_update_bytes_per_step'] == 20000 * 32 * 4
    assert rep['exchange_bytes_per_hop']['dp']['bytes'] == 1024
    assert rep['sweep'][0]['hot_fraction'] == 0.1
    assert rep['sweep'][0]['live_rows'] == 400


def test_sparse_report_respects_child_deadline(bench, monkeypatch):
    """Too little left on the child budget: the drill is skipped, never
    built — the flagship metric's deadline wins."""
    def boom(*_a, **_k):
        raise AssertionError("drill must not build under a tight deadline")
    monkeypatch.setattr(bench, '_run_sparse_drill', boom)
    monkeypatch.setenv('BENCH_CHILD_DEADLINE',
                       str(bench.time.time() + 60))
    rep = bench._sparse_report()
    assert rep == {'skipped': 'child deadline too close'}


def test_total_failure_fallback_carries_error(bench, capsys, monkeypatch):
    """Only when NO metric line could be produced does top-level
    "error" appear — and it names the measurement failures, with probe
    state still separate."""
    def fake_run_child(mode, timeout):
        return None, f"rc=1 (mode={mode}): boom"
    monkeypatch.setattr(bench, '_run_child', fake_run_child)
    bench.main()
    doc = _emitted_line(bench, capsys)
    assert doc['value'] == 0.0 and doc['backend'] == 'none'
    assert 'mode=cpu' in doc['error']
    assert 'mode=probe' not in doc['error']      # probe stays in "probe"
    assert doc['probe']['state'] == 'wedged'
