"""NDArray C API (src/ndarray/c_api_ndarray.cc; ref: include/mxnet/c_api.h
MXNDArray* block). Round-trips the dmlc binary container between the C
library and the Python serializer in both directions."""
import ctypes

import numpy as onp
import pytest

from conftest import build_native_lib


@pytest.fixture(scope='module')
def lib():
    lib = ctypes.CDLL(build_native_lib('libmxtpu_ndarray.so'))
    lib.MXGetLastError.restype = ctypes.c_char_p
    lib.MXNDArrayCreate.argtypes = [
        ctypes.POINTER(ctypes.c_uint32), ctypes.c_uint32, ctypes.c_int,
        ctypes.c_int, ctypes.c_int, ctypes.c_int,
        ctypes.POINTER(ctypes.c_void_p)]
    lib.MXNDArrayGetShape.argtypes = [
        ctypes.c_void_p, ctypes.POINTER(ctypes.c_uint32),
        ctypes.POINTER(ctypes.POINTER(ctypes.c_int64))]
    lib.MXNDArraySyncCopyFromCPU.argtypes = [
        ctypes.c_void_p, ctypes.c_void_p, ctypes.c_size_t]
    lib.MXNDArraySyncCopyToCPU.argtypes = [
        ctypes.c_void_p, ctypes.c_void_p, ctypes.c_size_t]
    return lib


def _make(lib, arr):
    shape = (ctypes.c_uint32 * arr.ndim)(*arr.shape)
    h = ctypes.c_void_p()
    flag = {'float32': 0, 'float64': 1, 'uint8': 3, 'int32': 4,
            'int64': 6}[arr.dtype.name]
    assert lib.MXNDArrayCreate(shape, arr.ndim, 1, 0, 0, flag,
                               ctypes.byref(h)) == 0
    c = onp.ascontiguousarray(arr)
    assert lib.MXNDArraySyncCopyFromCPU(
        h, c.ctypes.data_as(ctypes.c_void_p), c.size) == 0
    return h


def test_version_and_create(lib):
    v = ctypes.c_int()
    assert lib.MXGetVersion(ctypes.byref(v)) == 0 and v.value >= 20000
    a = onp.arange(12, dtype=onp.float32).reshape(3, 4)
    h = _make(lib, a)
    ndim = ctypes.c_uint32()
    pdata = ctypes.POINTER(ctypes.c_int64)()
    assert lib.MXNDArrayGetShape(h, ctypes.byref(ndim),
                                 ctypes.byref(pdata)) == 0
    assert ndim.value == 2 and [pdata[i] for i in range(2)] == [3, 4]
    back = onp.zeros_like(a)
    assert lib.MXNDArraySyncCopyToCPU(
        h, back.ctypes.data_as(ctypes.c_void_p), back.size) == 0
    assert onp.array_equal(back, a)
    assert lib.MXNDArrayFree(h) == 0


def test_c_save_python_load(lib, tmp_path):
    from mxnet_tpu import nd
    a = onp.arange(6, dtype=onp.float32).reshape(2, 3)
    b = onp.arange(4, dtype=onp.int64)
    ha, hb = _make(lib, a), _make(lib, b)
    handles = (ctypes.c_void_p * 2)(ha, hb)
    keys = (ctypes.c_char_p * 2)(b'weight', b'bias')
    fname = str(tmp_path / 'c_written.params').encode()
    assert lib.MXNDArraySave(fname, 2, handles, keys) == 0, \
        lib.MXGetLastError()
    loaded = nd.load(fname.decode())
    assert set(loaded) == {'weight', 'bias'}
    assert onp.array_equal(loaded['weight'].asnumpy(), a)
    assert onp.array_equal(loaded['bias'].asnumpy(), b)
    lib.MXNDArrayFree(ha)
    lib.MXNDArrayFree(hb)


def test_python_save_c_load(lib, tmp_path):
    from mxnet_tpu import nd
    fname = str(tmp_path / 'py_written.params')
    nd.save(fname, {'w': nd.array(onp.ones((4, 2), onp.float32) * 3),
                    'b': nd.array(onp.arange(5, dtype=onp.int32))})
    n = ctypes.c_uint32()
    arrs = ctypes.POINTER(ctypes.c_void_p)()
    nn = ctypes.c_uint32()
    names = ctypes.POINTER(ctypes.c_char_p)()
    assert lib.MXNDArrayLoad(fname.encode(), ctypes.byref(n),
                             ctypes.byref(arrs), ctypes.byref(nn),
                             ctypes.byref(names)) == 0, lib.MXGetLastError()
    assert n.value == 2 and nn.value == 2
    got = {}
    for i in range(n.value):
        h = ctypes.c_void_p(arrs[i])
        ndim = ctypes.c_uint32()
        pdata = ctypes.POINTER(ctypes.c_int64)()
        lib.MXNDArrayGetShape(h, ctypes.byref(ndim), ctypes.byref(pdata))
        shape = tuple(pdata[j] for j in range(ndim.value))
        dt = ctypes.c_int()
        lib.MXNDArrayGetDType(h, ctypes.byref(dt))
        np_dt = {0: onp.float32, 4: onp.int32}[dt.value]
        out = onp.zeros(shape, np_dt)
        lib.MXNDArraySyncCopyToCPU(
            h, out.ctypes.data_as(ctypes.c_void_p), out.size)
        got[names[i].decode()] = out
        lib.MXNDArrayFree(h)
    lib.MXNDArrayListFree(n, arrs, nn, names)
    assert onp.allclose(got['w'], 3.0) and got['w'].shape == (4, 2)
    assert onp.array_equal(got['b'], onp.arange(5))


def test_error_paths(lib, tmp_path):
    h = ctypes.c_void_p()
    shape = (ctypes.c_uint32 * 1)(3)
    assert lib.MXNDArrayCreate(shape, 1, 1, 0, 0, 99,
                               ctypes.byref(h)) == -1
    assert b'dtype' in lib.MXGetLastError()
    n = ctypes.c_uint32()
    arrs = ctypes.POINTER(ctypes.c_void_p)()
    nn = ctypes.c_uint32()
    names = ctypes.POINTER(ctypes.c_char_p)()
    bad = str(tmp_path / 'nope.params').encode()
    assert lib.MXNDArrayLoad(bad, ctypes.byref(n), ctypes.byref(arrs),
                             ctypes.byref(nn), ctypes.byref(names)) == -1
