"""Model zoo forward checks (ref: tests/python/unittest/test_gluon_model_zoo.py).
A representative subset per family; the full 15-model sweep runs in CI-nightly
fashion via scripts, not here (keeps the suite fast)."""
import numpy as onp
import pytest

import mxnet_tpu as mx
from mxnet_tpu import nd
from mxnet_tpu.gluon.model_zoo.vision import get_model

MODELS = ['alexnet', 'squeezenet1.0', 'mobilenetv2_1.0', 'resnet18_v1',
          'densenet121']


@pytest.mark.parametrize('name', MODELS)
def test_model_forward(name):
    net = get_model(name, classes=10)
    net.initialize(mx.init.Xavier())
    x = nd.array(onp.random.rand(1, 3, 224, 224).astype(onp.float32))
    out = net(x)
    assert out.shape == (1, 10)
    assert onp.isfinite(out.asnumpy()).all()


def test_get_model_unknown():
    with pytest.raises(ValueError):
        get_model('resnet9999_v9')


def test_model_zoo_list_complete():
    """Every family the reference model zoo ships is constructible
    (ref: python/mxnet/gluon/model_zoo/vision/__init__.py)."""
    from mxnet_tpu.gluon.model_zoo import vision
    for fam in ['alexnet', 'vgg11', 'vgg13', 'vgg16', 'vgg19', 'vgg11_bn',
                'squeezenet1.0', 'squeezenet1.1', 'densenet121',
                'densenet161', 'densenet169', 'densenet201', 'inceptionv3',
                'mobilenet1.0', 'mobilenet0.5', 'mobilenetv2_1.0',
                'resnet18_v1', 'resnet34_v1', 'resnet50_v1', 'resnet101_v1',
                'resnet152_v1', 'resnet18_v2', 'resnet34_v2', 'resnet50_v2',
                'resnet101_v2', 'resnet152_v2']:
        net = get_model(fam, classes=10)
        assert net is not None
