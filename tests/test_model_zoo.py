"""Model zoo forward checks (ref: tests/python/unittest/test_gluon_model_zoo.py).
A representative subset per family; the full 15-model sweep runs in CI-nightly
fashion via scripts, not here (keeps the suite fast)."""
import numpy as onp
import pytest

import mxnet_tpu as mx
from mxnet_tpu import nd
from mxnet_tpu.gluon.model_zoo.vision import get_model

MODELS = ['alexnet', 'squeezenet1.0', 'resnet18_v1',
          # the two heaviest forwards ride the slow tier; both families
          # stay constructible via test_model_zoo_list_complete
          pytest.param('mobilenetv2_1.0', marks=pytest.mark.slow),
          pytest.param('densenet121', marks=pytest.mark.slow)]


@pytest.mark.parametrize('name', MODELS)
def test_model_forward(name):
    net = get_model(name, classes=10)
    net.initialize(mx.init.Xavier())
    x = nd.array(onp.random.rand(1, 3, 224, 224).astype(onp.float32))
    out = net(x)
    assert out.shape == (1, 10)
    assert onp.isfinite(out.asnumpy()).all()


def test_get_model_unknown():
    with pytest.raises(ValueError):
        get_model('resnet9999_v9')


def test_model_zoo_list_complete():
    """Every family the reference model zoo ships is constructible
    (ref: python/mxnet/gluon/model_zoo/vision/__init__.py)."""
    from mxnet_tpu.gluon.model_zoo import vision
    for fam in ['alexnet', 'vgg11', 'vgg13', 'vgg16', 'vgg19', 'vgg11_bn',
                'squeezenet1.0', 'squeezenet1.1', 'densenet121',
                'densenet161', 'densenet169', 'densenet201', 'inceptionv3',
                'mobilenet1.0', 'mobilenet0.5', 'mobilenetv2_1.0',
                'resnet18_v1', 'resnet34_v1', 'resnet50_v1', 'resnet101_v1',
                'resnet152_v1', 'resnet18_v2', 'resnet34_v2', 'resnet50_v2',
                'resnet101_v2', 'resnet152_v2']:
        net = get_model(fam, classes=10)
        assert net is not None


def test_model_store_pretrained_end_to_end(tmp_path, monkeypatch):
    """get_model(..., pretrained=True) resolves weights through the model
    store (repo fetch -> sha1 check -> cache -> binary .params load) and
    reproduces the exact logits of the network that published the file
    (ref: gluon/model_zoo/model_store.py:34 + vision get_* loaders)."""
    import hashlib
    import numpy as onp
    import mxnet_tpu as mx
    from mxnet_tpu.gluon.model_zoo import model_store
    from mxnet_tpu.gluon.model_zoo.vision import get_model

    # "publish" a resnet18_v1 params file into a local repo dir
    mx.random.seed(3)
    src_net = get_model('resnet18_v1', classes=10)
    src_net.initialize(mx.init.Xavier())
    x = mx.nd.array(onp.random.RandomState(0)
                    .randn(2, 3, 32, 32).astype(onp.float32))
    ref_logits = src_net(x).asnumpy()

    repo = tmp_path / 'repo' / 'gluon' / 'models'
    repo.mkdir(parents=True)
    tmp_params = tmp_path / 'published.params'
    src_net.save_parameters(str(tmp_params))
    sha1 = hashlib.sha1(tmp_params.read_bytes()).hexdigest()
    monkeypatch.setitem(model_store._model_sha1, 'resnet18_v1', sha1)
    fpath = repo / f'resnet18_v1-{sha1[:8]}.params'
    tmp_params.rename(fpath)
    monkeypatch.setenv('MXNET_GLUON_REPO', 'file://' + str(tmp_path / 'repo'))

    cache = tmp_path / 'cache'
    net = get_model('resnet18_v1', pretrained=True, classes=10,
                    root=str(cache))
    out = net(x).asnumpy()
    assert onp.allclose(out, ref_logits, atol=1e-5)
    # cached copy hit on second load (delete the repo to prove it)
    fpath.unlink()
    net2 = get_model('resnet18_v1', pretrained=True, classes=10,
                     root=str(cache))
    assert onp.allclose(net2(x).asnumpy(), ref_logits, atol=1e-5)


def test_model_store_zip_and_checksum(tmp_path, monkeypatch):
    """Zip-packaged repo files are unzipped into the cache; checksum
    mismatches are rejected."""
    import hashlib
    import zipfile
    import numpy as onp
    import pytest
    import mxnet_tpu as mx
    from mxnet_tpu.gluon.model_zoo import model_store

    mx.random.seed(4)
    from mxnet_tpu.gluon.model_zoo.vision import get_model
    net = get_model('squeezenet1.0', classes=10)
    net.initialize(mx.init.Xavier())
    net(mx.nd.ones((1, 3, 64, 64)))   # materialize deferred shapes
    repo = tmp_path / 'repo' / 'gluon' / 'models'
    repo.mkdir(parents=True)
    params_tmp = tmp_path / 'published.params'
    net.save_parameters(str(params_tmp))
    sha1 = hashlib.sha1(params_tmp.read_bytes()).hexdigest()
    monkeypatch.setitem(model_store._model_sha1, 'squeezenet1.0', sha1)
    name = f'squeezenet1.0-{sha1[:8]}'
    with zipfile.ZipFile(repo / (name + '.zip'), 'w') as zf:
        zf.write(params_tmp, arcname=name + '.params')
    monkeypatch.setenv('MXNET_GLUON_REPO', str(tmp_path / 'repo'))
    out = model_store.get_model_file('squeezenet1.0',
                                     root=str(tmp_path / 'cache'))
    assert out.endswith(name + '.params')

    # corrupted repo payload -> checksum rejects the fetched file
    with zipfile.ZipFile(repo / (name + '.zip'), 'w') as zf:
        zf.writestr(name + '.params', b'corrupted bytes')
    with pytest.raises(ValueError, match='different hash'):
        model_store.get_model_file('squeezenet1.0',
                                   root=str(tmp_path / 'cache2'))
