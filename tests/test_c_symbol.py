"""Symbol C API (src/symbol/c_api_symbol.cc; ref: include/mxnet/c_api.h
MXSymbol* block): pure-C++ load/inspect/round-trip of the framework's
symbol JSON, driven via ctypes against python-produced graphs."""
import ctypes
import json

import numpy as onp
import pytest

from conftest import build_native_lib


@pytest.fixture(scope='module')
def lib():
    lib = ctypes.CDLL(build_native_lib('libmxtpu_symbol.so'))
    lib.MXGetLastError.restype = ctypes.c_char_p
    lib.MXSymbolCreateFromJSON.argtypes = [ctypes.c_char_p,
                                           ctypes.POINTER(ctypes.c_void_p)]
    lib.MXSymbolCreateFromFile.argtypes = [ctypes.c_char_p,
                                           ctypes.POINTER(ctypes.c_void_p)]
    lib.MXSymbolSaveToJSON.argtypes = [ctypes.c_void_p,
                                       ctypes.POINTER(ctypes.c_char_p)]
    lib.MXSymbolSaveToFile.argtypes = [ctypes.c_void_p, ctypes.c_char_p]
    lib.MXSymbolListArguments.argtypes = [
        ctypes.c_void_p, ctypes.POINTER(ctypes.c_uint32),
        ctypes.POINTER(ctypes.POINTER(ctypes.c_char_p))]
    lib.MXSymbolListOutputs.argtypes = lib.MXSymbolListArguments.argtypes
    lib.MXSymbolGetName.argtypes = [ctypes.c_void_p,
                                    ctypes.POINTER(ctypes.c_char_p),
                                    ctypes.POINTER(ctypes.c_int)]
    lib.MXSymbolGetAttr.argtypes = [ctypes.c_void_p, ctypes.c_char_p,
                                    ctypes.c_char_p,
                                    ctypes.POINTER(ctypes.c_char_p),
                                    ctypes.POINTER(ctypes.c_int)]
    lib.MXSymbolGetNumNodes.argtypes = [ctypes.c_void_p,
                                        ctypes.POINTER(ctypes.c_uint32)]
    lib.MXSymbolFree.argtypes = [ctypes.c_void_p]
    return lib


def _py_symbol():
    import mxnet_tpu as mx
    from mxnet_tpu import sym
    x = sym.Variable('data')
    with mx.AttrScope(ctx_group='g1'):
        w = sym.Variable('fc_weight')
    fc = sym.FullyConnected(x, w, None, num_hidden=4, no_bias=True,
                            name='fc')
    return sym.Activation(fc, act_type='relu', name='act')


def _load(lib, js):
    h = ctypes.c_void_p()
    rc = lib.MXSymbolCreateFromJSON(js.encode(), ctypes.byref(h))
    assert rc == 0, lib.MXGetLastError()
    return h


def _strs(lib, fn, h):
    n = ctypes.c_uint32()
    arr = ctypes.POINTER(ctypes.c_char_p)()
    assert fn(h, ctypes.byref(n), ctypes.byref(arr)) == 0
    return [arr[i].decode() for i in range(n.value)]


def test_load_and_introspect(lib):
    s = _py_symbol()
    h = _load(lib, s.tojson())
    assert _strs(lib, lib.MXSymbolListArguments, h) == s.list_arguments()
    assert _strs(lib, lib.MXSymbolListOutputs, h) == s.list_outputs()
    name = ctypes.c_char_p()
    ok = ctypes.c_int()
    assert lib.MXSymbolGetName(h, ctypes.byref(name),
                               ctypes.byref(ok)) == 0
    assert ok.value == 1 and name.value.decode() == s.name
    n = ctypes.c_uint32()
    assert lib.MXSymbolGetNumNodes(h, ctypes.byref(n)) == 0
    assert n.value == len(json.loads(s.tojson())['nodes'])
    lib.MXSymbolFree(h)


def test_attrs_visible_from_c(lib):
    s = _py_symbol()
    h = _load(lib, s.tojson())
    out = ctypes.c_char_p()
    ok = ctypes.c_int()
    assert lib.MXSymbolGetAttr(h, b'fc_weight', b'__ctx_group__',
                               ctypes.byref(out), ctypes.byref(ok)) == 0
    assert ok.value == 1 and out.value == b'g1'
    # missing attr: success=0, rc=0
    assert lib.MXSymbolGetAttr(h, b'fc_weight', b'nope',
                               ctypes.byref(out), ctypes.byref(ok)) == 0
    assert ok.value == 0
    # missing node: rc != 0 with message
    assert lib.MXSymbolGetAttr(h, b'ghost', b'k', ctypes.byref(out),
                               ctypes.byref(ok)) != 0
    assert b'ghost' in lib.MXGetLastError()
    lib.MXSymbolFree(h)


def test_roundtrip_reloads_in_python(lib, tmp_path):
    """C re-serialization loads back in python with identical structure
    and numerics."""
    import mxnet_tpu as mx
    from mxnet_tpu import sym, test_utils
    s = _py_symbol()
    h = _load(lib, s.tojson())
    path = str(tmp_path / 'c_roundtrip-symbol.json').encode()
    assert lib.MXSymbolSaveToFile(h, path) == 0
    lib.MXSymbolFree(h)
    s2 = sym.load(path.decode())
    assert test_utils.same_symbol_structure(s, s2)
    # numerics through the reloaded graph
    rng = onp.random.RandomState(0)
    binds = {'data': mx.nd.array(rng.randn(2, 8).astype('float32')),
             'fc_weight': mx.nd.array(rng.randn(4, 8).astype('float32'))}
    onp.testing.assert_allclose(s.eval_dict(binds).asnumpy(),
                                s2.eval_dict(binds).asnumpy(), rtol=1e-6)


def test_file_and_error_paths(lib, tmp_path):
    h = ctypes.c_void_p()
    assert lib.MXSymbolCreateFromFile(b'/nope/missing.json',
                                      ctypes.byref(h)) != 0
    assert lib.MXSymbolCreateFromJSON(b'{"nodes": "bogus"}',
                                      ctypes.byref(h)) != 0
    assert b'invalid symbol JSON' in lib.MXGetLastError()
    # out-of-range input ref rejected
    bad = json.dumps({'nodes': [{'op': 'null', 'name': 'x', 'attrs': {},
                                 'inputs': [[5, 0, 0]]}],
                      'heads': [[0, 0, 0]]})
    assert lib.MXSymbolCreateFromJSON(bad.encode(), ctypes.byref(h)) != 0


def test_unicode_names_roundtrip(lib, tmp_path):
    """json.dumps ensure_ascii emits \\uXXXX escapes; the C parser must
    decode them (incl. a non-BMP surrogate pair) and round-trip to UTF-8
    that python reads back identically."""
    js = json.dumps({
        'nodes': [{'op': 'null', 'name': 'fc_über_\U0001F600',
                   'attrs': {'k': 'vé'}, 'inputs': []}],
        'heads': [[0, 0, 0]]})
    assert '\\u' in js  # the escape path is actually exercised
    h = _load(lib, js)
    args = _strs(lib, lib.MXSymbolListArguments, h)
    assert args == ['fc_über_\U0001F600']
    out = ctypes.c_char_p()
    ok = ctypes.c_int()
    assert lib.MXSymbolGetAttr(h, 'fc_über_\U0001F600'.encode(),
                               b'k', ctypes.byref(out),
                               ctypes.byref(ok)) == 0
    assert ok.value == 1 and out.value.decode() == 'vé'
    cjson = ctypes.c_char_p()
    assert lib.MXSymbolSaveToJSON(h, ctypes.byref(cjson)) == 0
    re_parsed = json.loads(cjson.value.decode())
    assert re_parsed['nodes'][0]['name'] == 'fc_über_\U0001F600'
    lib.MXSymbolFree(h)
