"""NDArray basics (ref: tests/python/unittest/test_ndarray.py)."""
import numpy as onp
import pytest

import mxnet_tpu as mx
from mxnet_tpu import nd
from mxnet_tpu.test_utils import assert_almost_equal


def test_creation():
    a = nd.zeros((2, 3))
    assert a.shape == (2, 3)
    assert a.dtype == onp.float32
    b = nd.ones((2, 3))
    assert_almost_equal(b, onp.ones((2, 3)))
    c = nd.full((2, 2), 7.0)
    assert_almost_equal(c, onp.full((2, 2), 7.0))
    d = nd.array([[1, 2], [3, 4]])
    assert_almost_equal(d, [[1, 2], [3, 4]])
    e = nd.arange(0, 10, 2)
    assert_almost_equal(e, onp.arange(0, 10, 2, dtype=onp.float32))


def test_arithmetic():
    a = nd.array([[1., 2.], [3., 4.]])
    b = nd.array([[5., 6.], [7., 8.]])
    assert_almost_equal(a + b, [[6, 8], [10, 12]])
    assert_almost_equal(a - b, [[-4, -4], [-4, -4]])
    assert_almost_equal(a * b, [[5, 12], [21, 32]])
    assert_almost_equal(b / a, [[5, 3], [7 / 3, 2]], rtol=1e-6)
    assert_almost_equal(a + 1, [[2, 3], [4, 5]])
    assert_almost_equal(2 * a, [[2, 4], [6, 8]])
    assert_almost_equal(1 / a, [[1, .5], [1 / 3, .25]], rtol=1e-6)
    assert_almost_equal(a ** 2, [[1, 4], [9, 16]])
    assert_almost_equal(-a, [[-1, -2], [-3, -4]])


def test_inplace():
    a = nd.ones((2, 2))
    orig = a
    a += 1
    assert_almost_equal(orig, onp.full((2, 2), 2.0))
    a *= 3
    assert_almost_equal(orig, onp.full((2, 2), 6.0))


def test_comparisons():
    a = nd.array([1., 2., 3.])
    b = nd.array([2., 2., 2.])
    assert_almost_equal(a > b, [0, 0, 1])
    assert_almost_equal(a >= b, [0, 1, 1])
    assert_almost_equal(a == b, [0, 1, 0])
    assert_almost_equal(a != b, [1, 0, 1])


def test_indexing():
    a = nd.array(onp.arange(12).reshape(3, 4))
    assert_almost_equal(a[1], [4, 5, 6, 7])
    assert_almost_equal(a[1:3], [[4, 5, 6, 7], [8, 9, 10, 11]])
    assert a[2, 3].asscalar() == 11
    a[1] = 0
    assert_almost_equal(a[1], [0, 0, 0, 0])
    a[:] = 5
    assert_almost_equal(a, onp.full((3, 4), 5.0))


def test_shape_methods():
    a = nd.array(onp.arange(24).reshape(2, 3, 4))
    assert a.reshape(6, 4).shape == (6, 4)
    assert a.reshape((-1,)).shape == (24,)
    assert a.reshape(0, -1).shape == (2, 12)
    assert a.transpose().shape == (4, 3, 2)
    assert a.transpose(1, 0, 2).shape == (3, 2, 4)
    assert a.flatten().shape == (2, 12)
    assert a.expand_dims(0).shape == (1, 2, 3, 4)
    assert a.swapaxes(0, 2).shape == (4, 3, 2)
    assert nd.concat(a, a, dim=1).shape == (2, 6, 4)
    assert nd.stack(a, a, axis=0).shape == (2, 2, 3, 4)
    parts = a.split(3, axis=1)
    assert len(parts) == 3 and parts[0].shape == (2, 1, 4)


def test_reduce():
    a = nd.array(onp.arange(6).reshape(2, 3).astype(onp.float32))
    assert a.sum().asscalar() == 15
    assert_almost_equal(a.sum(axis=0), [3, 5, 7])
    assert_almost_equal(a.mean(axis=1), [1, 4])
    assert a.max().asscalar() == 5
    assert a.min().asscalar() == 0
    assert_almost_equal(a.argmax(axis=1), [2, 2])
    assert_almost_equal(nd.norm(a), onp.linalg.norm(onp.arange(6)))


def test_dot():
    a = onp.random.rand(3, 4).astype(onp.float32)
    b = onp.random.rand(4, 5).astype(onp.float32)
    assert_almost_equal(nd.dot(nd.array(a), nd.array(b)), a.dot(b), rtol=1e-5)
    x = onp.random.rand(2, 3, 4).astype(onp.float32)
    y = onp.random.rand(2, 4, 5).astype(onp.float32)
    assert_almost_equal(nd.batch_dot(nd.array(x), nd.array(y)),
                        onp.matmul(x, y), rtol=1e-5)


def test_astype_copy():
    a = nd.array([1.5, 2.5])
    b = a.astype('int32')
    assert b.dtype == onp.int32
    c = a.copy()
    c += 1
    assert_almost_equal(a, [1.5, 2.5])


def test_topk_sort():
    a = nd.array([[3., 1., 2.], [6., 5., 4.]])
    idx = nd.topk(a, k=2)
    assert_almost_equal(idx, [[0, 2], [0, 1]])
    vals = nd.topk(a, k=2, ret_typ='value')
    assert_almost_equal(vals, [[3, 2], [6, 5]])
    assert_almost_equal(nd.sort(a), [[1, 2, 3], [4, 5, 6]])
    assert_almost_equal(nd.argsort(a), [[1, 2, 0], [2, 1, 0]])


def test_save_load(tmp_path):
    fname = str(tmp_path / 'arrs')
    a = nd.array([1., 2.])
    b = nd.array([[3.]])
    nd.save(fname, {'a': a, 'b': b})
    loaded = nd.load(fname)
    assert_almost_equal(loaded['a'], a)
    assert_almost_equal(loaded['b'], b)
    nd.save(fname, [a, b])
    la = nd.load(fname)
    assert_almost_equal(la[0], a)


def test_wait_to_read():
    a = nd.ones((10, 10))
    b = a * 2
    b.wait_to_read()
    nd.waitall()
    assert_almost_equal(b, onp.full((10, 10), 2.0))


def test_context():
    a = nd.ones((2, 2), ctx=mx.cpu(0))
    assert a.context.device_type in ('cpu', 'gpu')
    b = a.as_in_context(mx.cpu(0))
    assert_almost_equal(b, a)


def test_one_hot_embedding_take():
    idx = nd.array([0, 2])
    oh = nd.one_hot(idx, depth=3)
    assert_almost_equal(oh, [[1, 0, 0], [0, 0, 1]])
    w = nd.array(onp.arange(12).reshape(4, 3).astype(onp.float32))
    emb = nd.embedding(idx, w)
    assert_almost_equal(emb, [[0, 1, 2], [6, 7, 8]])
    tk = nd.take(w, nd.array([1, 3]))
    assert_almost_equal(tk, [[3, 4, 5], [9, 10, 11]])
