"""tools/mxtpu_lint: the AST invariant checker checks itself.

Fixture violation matrix: one seeded violation per rule asserting
detection, one suppressed-by-comment case and one baselined case
asserting silence, plus the repo-level gate (``python -m
tools.mxtpu_lint`` must exit 0 at HEAD — every finding fixed or
explicitly grandfathered) and the regression tests for the real
signal-safety findings this PR's analyzer surfaced and fixed
(reentrant registry locks in telemetry/flight/membership).

Determinism: tools/flakiness_checker.py drives the lock-analyzer tests
3x — the cycle/reachability reports are pure functions of the source.
"""
import os
import subprocess
import sys
import textwrap
import threading

import pytest

REPO = os.path.abspath(os.path.join(os.path.dirname(__file__), os.pardir))
sys.path.insert(0, REPO)
sys.path.insert(0, os.path.join(REPO, 'tools'))

from mxtpu_lint.core import Baseline, FileIndex, run_rules  # noqa: E402
from mxtpu_lint.rules.host_sync import HostSyncRule  # noqa: E402
from mxtpu_lint.rules.jit_purity import JitPurityRule  # noqa: E402
from mxtpu_lint.rules.knobs import KnobDriftRule  # noqa: E402
from mxtpu_lint.rules.locks import (LockOrderRule,  # noqa: E402
                                    SignalSafetyRule)
from mxtpu_lint.rules.registry_drift import (RegistryDriftRule,  # noqa: E402
                                             scan_metrics)


def make_index(tmp_path, files):
    pkg = tmp_path / 'fixpkg'
    pkg.mkdir(parents=True, exist_ok=True)
    (pkg / '__init__.py').write_text('')
    for rel, src in files.items():
        p = pkg / rel
        p.parent.mkdir(parents=True, exist_ok=True)
        if not (p.parent / '__init__.py').exists():
            (p.parent / '__init__.py').write_text('')
        p.write_text(textwrap.dedent(src))
    return FileIndex(str(pkg))


# ---------------------------------------------------------------------------
# fixture violation matrix: each rule detects its seeded violation
# ---------------------------------------------------------------------------

def test_host_sync_detects_item_via_call_edge(tmp_path):
    idx = make_index(tmp_path, {'hot.py': '''
        def run(batch):
            return helper(batch)

        def helper(loss):
            return loss.item()
    '''})
    rule = HostSyncRule(roots=[('hot.py', 'run')],
                        hot_files=('hot.py',))
    found = rule.run(idx)
    assert any('.item()' in f.message and f.symbol == 'helper'
               for f in found), found


def test_host_sync_flags_block_until_ready_and_float(tmp_path):
    idx = make_index(tmp_path, {'hot.py': '''
        def run(arr, loss):
            arr.block_until_ready()
            return float(loss)
    '''})
    found = HostSyncRule(roots=[('hot.py', 'run')],
                         hot_files=('hot.py',)).run(idx)
    msgs = [f.message for f in found]
    assert any('block_until_ready' in m for m in msgs), msgs
    assert any('float()' in m for m in msgs), msgs


def test_host_sync_ignores_cold_functions(tmp_path):
    idx = make_index(tmp_path, {'hot.py': '''
        def run(batch):
            return batch

        def cold_restore(loss):
            return loss.item()
    '''})
    found = HostSyncRule(roots=[('hot.py', 'run')],
                         hot_files=('hot.py',)).run(idx)
    assert found == []


def test_jit_purity_detects_time_env_and_counters(tmp_path):
    idx = make_index(tmp_path, {'mod.py': '''
        import os
        import time
        import jax
        from telemetry import metrics as _metrics

        def step(x):
            t = time.time()
            flag = os.environ.get('SOME_FLAG')
            _metrics.inc('mxnet_tpu_fixture_total')
            return x * t

        compiled = jax.jit(step)

        def pure(x):
            return x + 1

        also = jax.jit(pure)
    '''})
    found = JitPurityRule().run(idx)
    msgs = [f.message for f in found]
    assert any('time.time()' in m for m in msgs), msgs
    assert any('os.environ' in m for m in msgs), msgs
    assert any('telemetry counter' in m for m in msgs), msgs
    assert all(f.symbol == 'step' for f in found), found


def test_jit_purity_decorator_and_global(tmp_path):
    idx = make_index(tmp_path, {'mod.py': '''
        import jax
        _calls = 0

        @jax.jit
        def step(x):
            global _calls
            _calls += 1
            return x
    '''})
    found = JitPurityRule().run(idx)
    assert any('global _calls' in f.message for f in found), found


def test_jit_purity_jax_random_not_flagged(tmp_path):
    idx = make_index(tmp_path, {'mod.py': '''
        import jax
        from jax import random

        def step(key):
            return random.normal(key, (2,))

        compiled = jax.jit(step)
    '''})
    assert JitPurityRule().run(idx) == []


def test_lock_order_cycle_detected(tmp_path):
    idx = make_index(tmp_path, {'locks.py': '''
        import threading

        class Box:
            def __init__(self):
                self._x = threading.Lock()
                self._y = threading.Lock()

            def f(self):
                with self._x:
                    with self._y:
                        pass

            def g(self):
                with self._y:
                    with self._x:
                        pass
    '''})
    found = LockOrderRule().run(idx)
    assert len(found) == 1, found
    assert 'lock-order cycle' in found[0].message
    assert 'Box._x' in found[0].message and 'Box._y' in found[0].message


def test_lock_order_cycle_through_call_edge(tmp_path):
    idx = make_index(tmp_path, {'locks.py': '''
        import threading

        _a = threading.Lock()
        _b = threading.Lock()

        def take_b():
            with _b:
                pass

        def f():
            with _a:
                take_b()

        def g():
            with _b:
                with _a:
                    pass
    '''})
    found = LockOrderRule().run(idx)
    assert len(found) == 1, found
    assert 'call take_b()' in found[0].message


def test_lock_order_nested_same_order_is_clean(tmp_path):
    idx = make_index(tmp_path, {'locks.py': '''
        import threading

        class Box:
            def __init__(self):
                self._x = threading.Lock()
                self._y = threading.Lock()

            def f(self):
                with self._x:
                    with self._y:
                        pass

            def g(self):
                with self._x:
                    with self._y:
                        pass
    '''})
    assert LockOrderRule().run(idx) == []


def test_signal_safety_detects_blocking_handler_lock(tmp_path):
    idx = make_index(tmp_path, {'sig.py': '''
        import signal
        import threading

        _lk = threading.Lock()

        def handler(signum, frame):
            with _lk:
                pass

        signal.signal(signal.SIGTERM, handler)
    '''})
    found = SignalSafetyRule().run(idx)
    assert len(found) == 1, found
    assert '_lk' in found[0].message and 'signal handler' in \
        found[0].message


def test_signal_safety_rlock_and_timeout_are_exempt(tmp_path):
    idx = make_index(tmp_path, {'sig.py': '''
        import atexit
        import signal
        import threading

        _r = threading.RLock()
        _lk = threading.Lock()

        def handler(signum, frame):
            with _r:
                pass
            got = _lk.acquire(timeout=2.0)
            if got:
                _lk.release()

        def hook():
            with _r:
                pass

        signal.signal(signal.SIGTERM, handler)
        atexit.register(hook)
    '''})
    assert SignalSafetyRule().run(idx) == []


def test_signal_safety_sees_through_handler_factory(tmp_path):
    idx = make_index(tmp_path, {'sig.py': '''
        import signal
        import threading

        _lk = threading.Lock()

        def make_handler(prev):
            def handler(signum, frame):
                with _lk:
                    pass
            return handler

        signal.signal(signal.SIGTERM, make_handler(None))
    '''})
    found = SignalSafetyRule().run(idx)
    assert len(found) == 1, found


def test_knob_drift_detects_raw_env_read(tmp_path):
    idx = make_index(tmp_path, {'mod.py': '''
        import os
        flag = os.environ.get('MXTPU_FIXTURE_FLAG')
        other = os.environ['MXNET_TPU_FIXTURE_DIR']
        benign = os.environ.get('PATH')
    '''})
    found = KnobDriftRule(readme_text='').run(idx)
    syms = {f.symbol for f in found}
    assert syms == {'MXTPU_FIXTURE_FLAG', 'MXNET_TPU_FIXTURE_DIR'}, found


def test_knob_drift_env_writes_not_flagged(tmp_path):
    idx = make_index(tmp_path, {'mod.py': '''
        import os
        os.environ['MXTPU_CHILD_FLAG'] = '1'
    '''})
    assert KnobDriftRule(readme_text='').run(idx) == []


def test_knob_drift_registered_knob_must_be_in_readme(tmp_path):
    idx = make_index(tmp_path, {'config.py': '''
        def register(name, type_, default, help_):
            pass

        register('MXTPU_DOCUMENTED', str, '', 'ok')
        register('MXTPU_SECRET', str, '', 'undocumented')
    '''})
    found = KnobDriftRule(
        readme_text='MXTPU_DOCUMENTED is described here').run(idx)
    assert [f.symbol for f in found] == ['MXTPU_SECRET'], found


def test_registry_drift_unknown_fault_site_and_span(tmp_path):
    idx = make_index(tmp_path, {'mod.py': '''
        from resilience import faults as _faults
        from telemetry import trace as _trace

        def f():
            _faults.fire('io.decode')
            _faults.fire('io.bogus_site')
            with _trace.span('step.dispatch'):
                pass
            with _trace.span('step.bogus'):
                pass
    '''})
    rule = RegistryDriftRule(fault_sites={'io.decode'},
                             span_names={'step.dispatch'},
                             check_metrics=False)
    found = rule.run(idx)
    syms = {f.symbol for f in found}
    assert syms == {'io.bogus_site', 'step.bogus'}, found


def test_registry_drift_unknown_flight_note_kind(tmp_path):
    idx = make_index(tmp_path, {'mod.py': '''
        from telemetry import flight as _flight

        def _note(kind, **info):
            _flight.note(kind, **info)

        def f():
            _flight.note('fleet.straggler', rank=1)
            _flight.note('fleet.bogus_event', rank=1)
            _note('checkpoint.scrub', step=3)
            _note('checkpoint.bogus', step=3)
    '''})
    rule = RegistryDriftRule(fault_sites=set(), span_names=set(),
                             note_names={'fleet.straggler',
                                         'checkpoint.scrub'},
                             check_metrics=False)
    found = rule.run(idx)
    syms = {f.symbol for f in found}
    assert syms == {'fleet.bogus_event', 'checkpoint.bogus'}, found


def test_registry_drift_fleet_contract_declared():
    # the fleet namespace + note kinds are part of the shared contract
    from mxtpu_lint import contracts
    assert 'mxnet_tpu_fleet_' in contracts.SUBSYSTEM_METRICS
    assert {'fleet.straggler', 'fleet.step_regression',
            'fleet.loss_spike', 'fleet.comm_imbalance'} <= \
        contracts.FLIGHT_NOTE_NAMES


def test_registry_drift_fault_sites_parsed_from_registry(tmp_path):
    idx = make_index(tmp_path, {
        'resilience/faults.py': '''
            _SITES = {
                'io.decode': ('desc', ('raise',)),
            }

            def fire(site, occurrence=None):
                return None
        ''',
        'mod.py': '''
            from resilience import faults as _faults
            _faults.fire('io.decode')
            _faults.fire('not.registered')
        '''})
    found = RegistryDriftRule(check_metrics=False).run(idx)
    assert [f.symbol for f in found] == ['not.registered'], found


def test_registry_drift_metric_name_shape(tmp_path):
    idx = make_index(tmp_path, {'mod.py': '''
        from telemetry import metrics as _metrics
        _metrics.inc('mxnet_tpu_good_total')
        _metrics.inc('Bad-Name')
    '''})
    _names, errors = scan_metrics(idx)
    assert any(n == 'Bad-Name' and 'lowercase_snake' in p
               for _f, _l, n, p in errors), errors


def test_registry_drift_kind_collision(tmp_path):
    idx = make_index(tmp_path, {'mod.py': '''
        from telemetry import metrics as _metrics
        _metrics.inc('mxnet_tpu_thing')
        _metrics.observe('mxnet_tpu_thing', 1.0)
    '''})
    _names, errors = scan_metrics(idx)
    assert any(n == 'mxnet_tpu_thing' and 'multiple kinds' in p
               for _f, _l, n, p in errors), errors


# ---------------------------------------------------------------------------
# suppression + baseline machinery
# ---------------------------------------------------------------------------

def test_suppression_comment_with_reason_silences(tmp_path):
    idx = make_index(tmp_path, {'mod.py': '''
        import os
        a = os.environ.get('MXTPU_OK_FLAG')  # lint: knob-drift-ok fixture reason
        b = os.environ.get('MXTPU_BAD_FLAG')
    '''})
    result = run_rules(idx, [KnobDriftRule(readme_text='')])
    assert [f.symbol for f in result.new] == ['MXTPU_BAD_FLAG']
    assert [(f.symbol, r) for f, r in result.suppressed] == \
        [('MXTPU_OK_FLAG', 'fixture reason')]


def test_suppression_without_reason_does_not_count(tmp_path):
    idx = make_index(tmp_path, {'mod.py': '''
        import os
        a = os.environ.get('MXTPU_OK_FLAG')  # lint: knob-drift-ok
    '''})
    result = run_rules(idx, [KnobDriftRule(readme_text='')])
    assert [f.symbol for f in result.new] == ['MXTPU_OK_FLAG']
    assert result.suppressed == []


def test_suppression_comment_line_above(tmp_path):
    idx = make_index(tmp_path, {'mod.py': '''
        import os
        # lint: knob-drift-ok reason on the line above
        a = os.environ.get('MXTPU_OK_FLAG')
    '''})
    result = run_rules(idx, [KnobDriftRule(readme_text='')])
    assert result.new == []
    assert len(result.suppressed) == 1


def test_baseline_silences_and_reports_stale(tmp_path):
    src = {'mod.py': '''
        import os
        a = os.environ.get('MXTPU_GRANDFATHERED')
    '''}
    idx = make_index(tmp_path, src)
    rule = KnobDriftRule(readme_text='')
    first = run_rules(idx, [rule])
    assert len(first.new) == 1
    bl = Baseline()
    bl.add(first.new[0], 'fixture: grandfathered')
    second = run_rules(idx, [rule], baseline=bl)
    assert second.new == [] and len(second.baselined) == 1
    assert second.clean
    # a stale entry (finding no longer produced) is reported, not kept
    bl2 = Baseline({'deadbeefdeadbeef': {'rule': 'knob-drift',
                                         'path': 'x', 'line': 1,
                                         'message': 'gone',
                                         'reason': 'old'}})
    third = run_rules(idx, [rule], baseline=bl2)
    assert len(third.new) == 1 and third.stale == ['deadbeefdeadbeef']


def test_warning_severity_reports_but_does_not_fail(tmp_path):
    idx = make_index(tmp_path, {'mod.py': '''
        import os
        a = os.environ.get('MXTPU_WARNED')
    '''})

    class WarningKnobRule(KnobDriftRule):
        severity = 'warning'

    result = run_rules(idx, [WarningKnobRule(readme_text='')])
    assert len(result.new) == 1
    assert result.new[0].severity == 'warning'
    assert 'warning:' in result.new[0].format()
    assert result.errors == [] and result.clean


def test_baseline_fingerprint_survives_line_moves(tmp_path):
    idx1 = make_index(tmp_path / 'a', {'mod.py': '''
        import os
        a = os.environ.get('MXTPU_MOVED')
    '''})
    idx2 = make_index(tmp_path / 'b', {'mod.py': '''
        import os
        # an unrelated comment pushing the read down two lines

        a = os.environ.get('MXTPU_MOVED')
    '''})
    rule = KnobDriftRule(readme_text='')
    f1, f2 = rule.run(idx1)[0], rule.run(idx2)[0]
    assert f1.line != f2.line
    assert f1.fingerprint == f2.fingerprint


# ---------------------------------------------------------------------------
# the repo-level gate (tier-1 wiring + acceptance criterion)
# ---------------------------------------------------------------------------

def test_repo_is_lint_clean():
    """``python -m tools.mxtpu_lint`` exits 0 at HEAD: every finding is
    fixed or explicitly baselined with a reason."""
    res = subprocess.run(
        [sys.executable, '-m', 'tools.mxtpu_lint'],
        cwd=REPO, capture_output=True, text=True, timeout=300)
    assert res.returncode == 0, res.stdout + res.stderr
    assert 'mxtpu_lint: 0 new finding(s)' in res.stdout


def test_cli_rule_selection_and_list():
    res = subprocess.run(
        [sys.executable, '-m', 'tools.mxtpu_lint', '--list-rules'],
        cwd=REPO, capture_output=True, text=True, timeout=120)
    assert res.returncode == 0
    for rid in ('host-sync', 'jit-purity', 'lock-order',
                'signal-safety', 'knob-drift', 'registry-drift'):
        assert rid in res.stdout
    res = subprocess.run(
        [sys.executable, '-m', 'tools.mxtpu_lint', '--rules',
         'knob-drift'], cwd=REPO, capture_output=True, text=True,
        timeout=300)
    assert res.returncode == 0, res.stdout + res.stderr


def test_cli_fails_on_seeded_violation(tmp_path):
    pkg = tmp_path / 'badpkg'
    pkg.mkdir()
    (pkg / '__init__.py').write_text('')
    (pkg / 'mod.py').write_text(
        "import os\nx = os.environ.get('MXTPU_SEEDED')\n")
    res = subprocess.run(
        [sys.executable, '-m', 'tools.mxtpu_lint', '--baseline', 'none',
         str(pkg)], cwd=REPO, capture_output=True, text=True,
        timeout=300)
    assert res.returncode == 1, res.stdout + res.stderr
    assert 'MXTPU_SEEDED' in res.stderr


def test_cli_write_baseline_roundtrip(tmp_path):
    pkg = tmp_path / 'blpkg'
    pkg.mkdir()
    (pkg / '__init__.py').write_text('')
    (pkg / 'mod.py').write_text(
        "import os\nx = os.environ.get('MXTPU_TO_GRANDFATHER')\n")
    bl = tmp_path / 'bl.json'
    res = subprocess.run(
        [sys.executable, '-m', 'tools.mxtpu_lint', '--baseline',
         str(bl), '--write-baseline', str(pkg)],
        cwd=REPO, capture_output=True, text=True, timeout=300)
    assert res.returncode == 0, res.stdout + res.stderr
    res = subprocess.run(
        [sys.executable, '-m', 'tools.mxtpu_lint', '--baseline',
         str(bl), str(pkg)],
        cwd=REPO, capture_output=True, text=True, timeout=300)
    assert res.returncode == 0, res.stdout + res.stderr
    assert '1 baselined' in res.stdout


# ---------------------------------------------------------------------------
# regression tests for the real signal-safety findings this PR fixed
# ---------------------------------------------------------------------------

def _assert_reentrant(lock, what):
    """A signal handler re-entering on the SAME thread must not
    self-deadlock: the second non-blocking acquire succeeds iff the
    lock is reentrant."""
    assert lock.acquire(blocking=False), f'{what}: first acquire failed'
    try:
        got = lock.acquire(blocking=False)
        assert got, (f'{what} is not reentrant — a signal interrupting '
                     f'its critical section self-deadlocks the handler')
        lock.release()
    finally:
        lock.release()


def test_flight_recorder_lock_reentrant():
    from mxnet_tpu.telemetry import flight
    _assert_reentrant(flight._recorder_lock, 'flight._recorder_lock')


def test_trace_rings_lock_reentrant_and_span_under_held_lock():
    from mxnet_tpu.telemetry import trace
    _assert_reentrant(trace._rings_lock, 'trace._rings_lock')
    # functional: first span of a thread registers its ring while THIS
    # thread already holds the registry lock (= a SIGTERM save tracing
    # checkpoint spans interrupted mid-registration) — must complete
    trace.clear()
    trace.enable()
    try:
        assert trace._rings_lock.acquire(blocking=False)
        try:
            with trace.span('checkpoint.snapshot'):
                pass
        finally:
            trace._rings_lock.release()
        assert trace.stats()['spans_total'] >= 1
    finally:
        trace.disable()
        trace.clear()


def test_metric_lock_reentrant():
    from mxnet_tpu.telemetry import metrics
    c = metrics.Counter('mxnet_tpu_lint_fixture_total')
    _assert_reentrant(c._lock, 'Metric._lock')


def test_membership_lock_reentrant():
    from mxnet_tpu.parallel.dist import Membership
    ms = Membership(rank=0, world=1, start=False)
    _assert_reentrant(ms._lock, 'Membership._lock')
    # the concrete PR-8-class scenario: the checkpoint SIGTERM handler
    # records the membership view in the manifest while the interrupted
    # frame (this thread) holds the membership lock
    assert ms._lock.acquire(blocking=False)
    try:
        view = ms.view()
        assert view is None or isinstance(view, dict)
        ms.lost_peers()
    finally:
        ms._lock.release()


def test_analyzer_confirms_fixes_on_live_tree():
    """The shipped tree carries zero signal-safety/lock-order findings
    (the analyzer that found the flight/trace/membership bugs now
    proves their fixes)."""
    res = subprocess.run(
        [sys.executable, '-m', 'tools.mxtpu_lint', '--rules',
         'signal-safety,lock-order', '--baseline', 'none'],
        cwd=REPO, capture_output=True, text=True, timeout=300)
    assert res.returncode == 0, res.stdout + res.stderr


# ---------------------------------------------------------------------------
# thin wrappers: exit codes preserved
# ---------------------------------------------------------------------------

def test_check_trace_wrapper_exit_codes(tmp_path):
    tool = os.path.join(REPO, 'tools', 'check_trace.py')
    good = tmp_path / 'good.json'
    good.write_text('{"traceEvents": [{"ph": "B", "name": "s", '
                    '"ts": 1, "pid": 1, "tid": 1}, {"ph": "E", '
                    '"name": "s", "ts": 2, "pid": 1, "tid": 1}]}')
    bad = tmp_path / 'bad.json'
    bad.write_text('{"traceEvents": [{"ph": "E", "name": "s", '
                   '"ts": 2, "pid": 1, "tid": 1}]}')
    ok = subprocess.run([sys.executable, tool, str(good)],
                        capture_output=True, text=True, timeout=120)
    assert ok.returncode == 0 and 'balanced B/E' in ok.stdout
    fail = subprocess.run([sys.executable, tool, str(bad)],
                          capture_output=True, text=True, timeout=120)
    assert fail.returncode == 1 and "orphan 'E'" in fail.stderr
    usage = subprocess.run([sys.executable, tool],
                           capture_output=True, text=True, timeout=120)
    assert usage.returncode == 2


def test_check_telemetry_names_wrapper():
    tool = os.path.join(REPO, 'tools', 'check_telemetry_names.py')
    res = subprocess.run([sys.executable, tool], capture_output=True,
                         text=True, timeout=300)
    assert res.returncode == 0, res.stderr
    assert 'telemetry names OK' in res.stdout


# ---------------------------------------------------------------------------
# determinism: the lock analyzer is a pure function of the source
# ---------------------------------------------------------------------------

def test_lock_analyzer_deterministic_3x():
    """Drives tools/flakiness_checker.py over the lock-analyzer tests
    3x (distinct seeds): cycle detection and signal-safety reachability
    must be exactly reproducible — hash/set ordering may never leak
    into the findings."""
    tools = os.path.join(REPO, 'tools', 'flakiness_checker.py')
    res = subprocess.run(
        [sys.executable, tools,
         'tests/test_lint.py::test_lock_order_cycle_detected',
         '-n', '3'],
        cwd=REPO, capture_output=True, text=True, timeout=600)
    assert res.returncode == 0, res.stdout + res.stderr
    assert '3/3 passed' in res.stdout
    res = subprocess.run(
        [sys.executable, tools,
         'tests/test_lint.py::test_signal_safety_detects_blocking_handler_lock',
         '-n', '3'],
        cwd=REPO, capture_output=True, text=True, timeout=600)
    assert res.returncode == 0, res.stdout + res.stderr
    assert '3/3 passed' in res.stdout
