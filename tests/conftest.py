"""Test config: run on a virtual 8-device CPU mesh (SURVEY §4 pattern —
multi-device tests without a cluster, like the reference's multiple logical
mx.gpu(i) contexts in one process)."""
import os
import sys

os.environ['JAX_PLATFORMS'] = 'cpu'
prev = os.environ.get('XLA_FLAGS', '')
if '--xla_force_host_platform_device_count' not in prev:
    os.environ['XLA_FLAGS'] = (
        prev + ' --xla_force_host_platform_device_count=8').strip()
# Tests are CPU-hermetic. jax may already be imported (TPU-tunnel site
# hooks import it at interpreter start and freeze the env-derived platform
# selection), so force the platform through the config API too.
import jax  # noqa: E402

jax.config.update('jax_platforms', 'cpu')

import numpy as onp  # noqa: E402
import pytest  # noqa: E402


def pytest_configure(config):
    config.addinivalue_line(
        'markers',
        'slow: long e2e drills and model sweeps excluded from the tier-1 '
        "budget (`-m 'not slow'`). Everything marked slow is either "
        'duplicated by a dryrun_multichip stage that runs in every '
        'MULTICHIP round, or a multi-minute model-zoo one-off; run them '
        'with `pytest -m slow`.')


@pytest.fixture(autouse=True)
def _seed():
    import mxnet_tpu as mx
    # MXNET_TEST_SEED: per-trial seed injected by tools/flakiness_checker
    # (ref: the reference's with_seed decorator env override)
    seed = int(os.environ.get('MXNET_TEST_SEED', 0))
    mx.random.seed(seed)
    onp.random.seed(seed)
    yield


def build_native_lib(so_name):
    """Path to mxnet_tpu/_lib/<so_name>, running `make` in src/ if it is
    missing; pytest.skip when the toolchain can't produce it. Shared by
    the native-library test modules."""
    lib = os.path.normpath(os.path.join(
        os.path.dirname(__file__), os.pardir, 'mxnet_tpu', '_lib', so_name))
    if not os.path.exists(lib):
        import subprocess
        src = os.path.normpath(os.path.join(
            os.path.dirname(__file__), os.pardir, 'src'))
        subprocess.run(['make'], cwd=src, check=False)
    if not os.path.exists(lib):
        pytest.skip(f"native library {so_name} not built")
    return lib
