"""Profiler semantics (ref: python/mxnet/profiler.py + the per-op rows the
reference's engine wrapping produces, src/profiler/profiler.h:299)."""
import json
import os

import mxnet_tpu as mx
from mxnet_tpu import nd, profiler


def _reset():
    profiler.set_config(profile_imperative=False, profile_all=False,
                        aggregate_stats=False, profile_sync=False,
                        jax_trace_dir=None)


def test_per_op_rows_and_aggregate_table():
    profiler.set_config(profile_imperative=True, aggregate_stats=True)
    profiler.start()
    a = nd.ones((16, 16))
    for _ in range(3):
        nd.dot(a, a)
    profiler.stop()
    table = profiler.dumps()
    assert 'dot' in table and 'Total Count' in table
    row = [ln for ln in table.splitlines() if ln.startswith('dot')][0]
    assert int(row.split()[1]) == 3
    evs = json.loads(profiler.dumps(format='json'))['traceEvents']
    ops = [e for e in evs if e['cat'] == 'operator']
    assert len(ops) >= 3 and all('dur' in e for e in ops)
    _reset()


def test_set_config_rejects_unknown_keys():
    import pytest
    with pytest.raises(mx.base.MXNetError):
        profiler.set_config(not_a_real_key=True)


def test_profiling_off_by_default():
    profiler.start()
    a = nd.ones((4, 4))
    nd.dot(a, a)
    profiler.stop()
    evs = json.loads(profiler.dumps(format='json'))['traceEvents']
    assert not [e for e in evs if e['cat'] == 'operator']
    _reset()


def test_jax_trace_started_via_api(tmp_path):
    profiler.set_config(jax_trace_dir=str(tmp_path))
    profiler.start()
    nd.dot(nd.ones((8, 8)), nd.ones((8, 8))).wait_to_read()
    profiler.stop()
    files = [f for _, _, fs in os.walk(str(tmp_path)) for f in fs]
    assert files, "no jax trace written"
    _reset()


def test_start_clears_events_and_stats_atomically():
    """ISSUE 1 satellite: start() must clear BOTH _events and _op_stats
    under _events_lock — a stale event surviving into the new run is the
    observable symptom of the old unlocked clear."""
    profiler.set_config(profile_imperative=True, aggregate_stats=True)
    profiler.start()
    a = nd.ones((4, 4))
    nd.dot(a, a)
    profiler.stop()
    assert json.loads(profiler.dumps(format='json'))['traceEvents']
    profiler.start()   # must reset both stores
    assert not json.loads(profiler.dumps(format='json'))['traceEvents']
    summary = profiler.get_summary()
    assert 'dot' not in summary
    profiler.stop()
    _reset()


def test_continuous_dump_extends_file_without_reemitting(tmp_path):
    """ISSUE 1 satellite: with continuous_dump, each dump() flushes only
    new events; the on-disk trace accumulates them exactly once."""
    fname = str(tmp_path / 'cont.json')
    profiler.set_config(filename=fname, continuous_dump=True)
    profiler.start()
    with profiler.scope('s1'):
        pass
    profiler.dump()
    first = json.load(open(fname))['traceEvents']
    assert [e['name'] for e in first].count('s1') == 2   # B + E
    with profiler.scope('s2'):
        pass
    profiler.dump()
    evs = json.load(open(fname))['traceEvents']
    names = [e['name'] for e in evs]
    assert names.count('s1') == 2 and names.count('s2') == 2
    # nothing re-emitted, nothing left in memory
    profiler.dump()
    assert len(json.load(open(fname))['traceEvents']) == 4
    assert not json.loads(profiler.dumps(format='json'))['traceEvents']
    # a NEW run overwrites the leftover file instead of merging into it
    profiler.start()
    with profiler.scope('s3'):
        pass
    profiler.dump()
    names = [e['name'] for e in json.load(open(fname))['traceEvents']]
    assert names.count('s3') == 2 and 's1' not in names
    profiler.stop()
    profiler.set_config(filename='profile.json', continuous_dump=False)
    _reset()


def test_scopes_and_counters_still_work(tmp_path):
    profiler.set_config(filename=str(tmp_path / 'p.json'))
    profiler.start()
    dom = profiler.Domain('test')
    with dom.new_task('work'):
        c = dom.new_counter('ctr', 1)
        c += 2
    profiler.stop()
    profiler.dump()
    data = json.load(open(str(tmp_path / 'p.json')))
    names = [e['name'] for e in data['traceEvents']]
    assert 'work' in names and 'ctr' in names
    _reset()
