"""Env-var configuration tier (ref: docs/faq/env_var.md surface)."""
import os

import pytest

import mxnet_tpu as mx
from mxnet_tpu import config
from mxnet_tpu.base import MXNetError


def test_declared_vars_typed_reads(monkeypatch):
    assert 'MXNET_HOME' in config.list_vars()
    monkeypatch.setenv('MXNET_KVSTORE_BIGARRAY_BOUND', '12345')
    assert config.get('MXNET_KVSTORE_BIGARRAY_BOUND') == 12345
    monkeypatch.setenv('MXNET_ENFORCE_DETERMINISM', 'true')
    assert config.get('MXNET_ENFORCE_DETERMINISM') is True
    monkeypatch.delenv('MXNET_KVSTORE_BIGARRAY_BOUND')
    assert config.get('MXNET_KVSTORE_BIGARRAY_BOUND') == 1000000


def test_unknown_and_invalid_rejected(monkeypatch):
    with pytest.raises(MXNetError, match='unknown'):
        config.get('MXNET_NOT_A_VAR')
    with pytest.raises(MXNetError, match='unknown'):
        config.set_env('MXNET_NOT_A_VAR', 1)
    monkeypatch.setenv('MXNET_SEED', 'not-an-int')
    with pytest.raises(MXNetError, match='not a valid'):
        config.get('MXNET_SEED')


def test_describe_documents_inert_vars():
    doc = config.describe('MXNET_ENGINE_TYPE')
    assert 'inert on TPU' in doc and 'XLA' in doc
    full = config.describe()
    assert 'MXNET_GLUON_REPO' in full


def test_subgraph_backend_env_default(monkeypatch):
    import numpy as onp
    from mxnet_tpu import nd
    from mxnet_tpu.gluon import nn
    monkeypatch.setenv('MXNET_SUBGRAPH_BACKEND', 'fuse_attention')
    net = nn.HybridSequential()
    net.add(nn.Dense(4, in_units=4))
    net.initialize(mx.init.Xavier())
    net.hybridize()
    assert net._subgraph_backend is not None
    assert net._subgraph_backend.name == 'fuse_attention'
    out = net(nd.ones((2, 4)))
    assert out.shape == (2, 4)
