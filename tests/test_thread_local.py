"""Thread-locality and threaded inference (ref:
tests/nightly/test_tlocal_racecondition.py, tests/python/unittest/
test_thread_local.py, and the thread-safe CachedOp suite
tests/cpp/thread_safety/)."""
import threading

import numpy as onp

import mxnet_tpu as mx
from mxnet_tpu import autograd, gluon, nd, sym


def test_context_stack_is_thread_local():
    results = {}

    def worker(idx):
        with mx.Context('cpu', idx):
            import time
            time.sleep(0.05)
            results[idx] = mx.current_context().device_id

    threads = [threading.Thread(target=worker, args=(i,)) for i in (0, 1)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    assert results == {0: 0, 1: 1}, results


def test_attr_scope_is_thread_local():
    seen = {}

    def worker(tag):
        with mx.AttrScope(ctx_group=tag):
            import time
            time.sleep(0.05)
            s = sym.Variable(f'v_{tag}')
            seen[tag] = s.attr('__ctx_group__')

    threads = [threading.Thread(target=worker, args=(t,))
               for t in ('a', 'b')]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    assert seen == {'a': 'a', 'b': 'b'}, seen


def test_autograd_recording_is_thread_local():
    flags = {}

    def recorder():
        x = nd.array(onp.ones((2, 2), 'float32'))
        x.attach_grad()
        with autograd.record():
            import time
            time.sleep(0.05)
            flags['rec'] = autograd.is_recording()
            y = nd.sum(x * 2)
        y.backward()
        flags['grad'] = x.grad.asnumpy()

    def bystander():
        import time
        time.sleep(0.02)
        flags['other'] = autograd.is_recording()

    t1 = threading.Thread(target=recorder)
    t2 = threading.Thread(target=bystander)
    t1.start(); t2.start(); t1.join(); t2.join()
    assert flags['rec'] is True
    assert flags['other'] is False
    onp.testing.assert_allclose(flags['grad'], 2 * onp.ones((2, 2)))


def test_threadsafe_hybridized_inference():
    """Concurrent forwards through ONE hybridized block from N threads
    produce correct, deterministic outputs (the thread-safe CachedOp
    contract, src/imperative/cached_op_threadsafe.cc)."""
    net = gluon.nn.HybridSequential()
    with net.name_scope():
        net.add(gluon.nn.Dense(32, activation='relu'))
        net.add(gluon.nn.Dense(8))
    net.initialize(mx.init.Xavier())
    net.hybridize()
    rng = onp.random.RandomState(0)
    xs = [rng.randn(4, 16).astype('float32') for _ in range(8)]
    expected = [net(nd.array(x)).asnumpy() for x in xs]

    outs = [None] * len(xs)
    errs = []

    def worker(i):
        try:
            outs[i] = net(nd.array(xs[i])).asnumpy()
        except Exception as e:  # pragma: no cover
            errs.append((i, e))

    threads = [threading.Thread(target=worker, args=(i,))
               for i in range(len(xs))]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    assert not errs, errs
    for got, want in zip(outs, expected):
        onp.testing.assert_allclose(got, want, rtol=1e-5, atol=1e-6)


def test_concurrent_hybridized_forward_parity():
    """N threads share ONE hybridized block and hammer it concurrently;
    every result must equal the serial output (ref:
    tests/cpp/thread_safety/thread_safety_test.cc — CachedOp used from
    many threads). jax dispatch is thread-safe; the block's jit cache is
    the shared mutable state under test."""
    import threading
    import numpy as onp
    import mxnet_tpu as mx
    from mxnet_tpu import nd
    from mxnet_tpu.gluon import nn

    net = nn.HybridSequential()
    net.add(nn.Dense(32, activation='relu'), nn.Dense(8))
    net.initialize(mx.init.Xavier())
    net.hybridize()
    rng = onp.random.RandomState(0)
    xs = [rng.randn(4, 16).astype(onp.float32) for _ in range(8)]
    expected = [net(nd.array(x)).asnumpy() for x in xs]

    errors = []
    results = [None] * len(xs)

    def worker(i):
        try:
            for _ in range(5):
                results[i] = net(nd.array(xs[i])).asnumpy()
        except Exception as e:  # pragma: no cover
            errors.append((i, e))

    threads = [threading.Thread(target=worker, args=(i,))
               for i in range(len(xs))]
    for t in threads:
        t.start()
    for t in threads:
        t.join(timeout=120)
    assert not errors, errors
    for got, want in zip(results, expected):
        onp.testing.assert_allclose(got, want, rtol=1e-6)


def test_concurrent_autograd_tapes_are_independent():
    """Each thread records its own tape on its own arrays; gradients
    must not bleed across threads (the reference keeps per-thread
    imperative state; here state is threading.local)."""
    import threading
    import numpy as onp
    from mxnet_tpu import nd, autograd

    errors = []

    def worker(seed):
        try:
            rng = onp.random.RandomState(seed)
            x = nd.array(rng.randn(8).astype(onp.float32))
            x.attach_grad()
            for _ in range(3):
                with autograd.record():
                    y = (x * x * seed).sum()
                y.backward()
                onp.testing.assert_allclose(
                    x.grad.asnumpy(), 2 * seed * x.asnumpy(), rtol=1e-5)
        except Exception as e:  # pragma: no cover
            errors.append((seed, e))

    threads = [threading.Thread(target=worker, args=(s,))
               for s in range(1, 7)]
    for t in threads:
        t.start()
    for t in threads:
        t.join(timeout=120)
    assert not errors, errors


def test_concurrent_kvstore_push_pull():
    """Many threads pushing/pulling distinct keys on one local kvstore
    (ref: thread-safety of KVStoreLocal)."""
    import threading
    import numpy as onp
    import mxnet_tpu as mx
    from mxnet_tpu import nd

    kv = mx.kv.create('local')
    for k in range(6):
        kv.init(k, nd.zeros((4,)))
    errors = []

    def worker(k):
        try:
            for i in range(10):
                kv.push(k, nd.ones((4,)) * (k + 1))
                out = nd.zeros((4,))
                kv.pull(k, out=out)
                assert float(out.asnumpy()[0]) != 0.0
        except Exception as e:  # pragma: no cover
            errors.append((k, e))

    threads = [threading.Thread(target=worker, args=(k,))
               for k in range(6)]
    for t in threads:
        t.start()
    for t in threads:
        t.join(timeout=120)
    assert not errors, errors
