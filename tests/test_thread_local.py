"""Thread-locality and threaded inference (ref:
tests/nightly/test_tlocal_racecondition.py, tests/python/unittest/
test_thread_local.py, and the thread-safe CachedOp suite
tests/cpp/thread_safety/)."""
import threading

import numpy as onp

import mxnet_tpu as mx
from mxnet_tpu import autograd, gluon, nd, sym


def test_context_stack_is_thread_local():
    results = {}

    def worker(idx):
        with mx.Context('cpu', idx):
            import time
            time.sleep(0.05)
            results[idx] = mx.current_context().device_id

    threads = [threading.Thread(target=worker, args=(i,)) for i in (0, 1)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    assert results == {0: 0, 1: 1}, results


def test_attr_scope_is_thread_local():
    seen = {}

    def worker(tag):
        with mx.AttrScope(ctx_group=tag):
            import time
            time.sleep(0.05)
            s = sym.Variable(f'v_{tag}')
            seen[tag] = s.attr('__ctx_group__')

    threads = [threading.Thread(target=worker, args=(t,))
               for t in ('a', 'b')]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    assert seen == {'a': 'a', 'b': 'b'}, seen


def test_autograd_recording_is_thread_local():
    flags = {}

    def recorder():
        x = nd.array(onp.ones((2, 2), 'float32'))
        x.attach_grad()
        with autograd.record():
            import time
            time.sleep(0.05)
            flags['rec'] = autograd.is_recording()
            y = nd.sum(x * 2)
        y.backward()
        flags['grad'] = x.grad.asnumpy()

    def bystander():
        import time
        time.sleep(0.02)
        flags['other'] = autograd.is_recording()

    t1 = threading.Thread(target=recorder)
    t2 = threading.Thread(target=bystander)
    t1.start(); t2.start(); t1.join(); t2.join()
    assert flags['rec'] is True
    assert flags['other'] is False
    onp.testing.assert_allclose(flags['grad'], 2 * onp.ones((2, 2)))


def test_threadsafe_hybridized_inference():
    """Concurrent forwards through ONE hybridized block from N threads
    produce correct, deterministic outputs (the thread-safe CachedOp
    contract, src/imperative/cached_op_threadsafe.cc)."""
    net = gluon.nn.HybridSequential()
    with net.name_scope():
        net.add(gluon.nn.Dense(32, activation='relu'))
        net.add(gluon.nn.Dense(8))
    net.initialize(mx.init.Xavier())
    net.hybridize()
    rng = onp.random.RandomState(0)
    xs = [rng.randn(4, 16).astype('float32') for _ in range(8)]
    expected = [net(nd.array(x)).asnumpy() for x in xs]

    outs = [None] * len(xs)
    errs = []

    def worker(i):
        try:
            outs[i] = net(nd.array(xs[i])).asnumpy()
        except Exception as e:  # pragma: no cover
            errs.append((i, e))

    threads = [threading.Thread(target=worker, args=(i,))
               for i in range(len(xs))]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    assert not errs, errs
    for got, want in zip(outs, expected):
        onp.testing.assert_allclose(got, want, rtol=1e-5, atol=1e-6)
