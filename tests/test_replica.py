"""Checkpoint survivability (ISSUE 10): peer replication over the
membership-style TCP side channel, background integrity scrubbing with
quarantine + bit-identical repair, any-replica restore, and the crash
matrix (receiver killed mid-transfer, sender killed between local
commit and replication, scrubber vs an injected bit flip)."""
import os
import shutil
import signal
import socket
import subprocess
import sys
import threading
import time

import numpy as onp
import pytest

import mxnet_tpu as mx
from mxnet_tpu import checkpoint, telemetry
from mxnet_tpu.base import MXNetError
from mxnet_tpu.checkpoint import (CheckpointManager, ReplicaManager,
                                  ReplicaPeer)
from mxnet_tpu.checkpoint import manifest as mf
from mxnet_tpu.parallel import dist
from mxnet_tpu.resilience import faults
from mxnet_tpu.resilience.elastic import stall_verdict

REPO = os.path.join(os.path.dirname(__file__), os.pardir)

PARAMS = {'w': onp.arange(12, dtype=onp.float32).reshape(3, 4),
          'b': onp.full((4,), 7.0, onp.float32)}


@pytest.fixture(autouse=True)
def _clean_globals():
    yield
    faults.disarm()
    dist.stop_membership()


def _pair(tmp_path, **rm_a_kw):
    """Two managers with cross-wired replication: a pushes to b."""
    mgr_a = CheckpointManager(str(tmp_path / 'a'), async_save=False,
                              replication=False)
    mgr_b = CheckpointManager(str(tmp_path / 'b'), async_save=False,
                              replication=False)
    rm_b = ReplicaManager(mgr_b, rank=1, peers=[], port=0,
                          scrub_seconds=0, resync=False)
    rm_a = ReplicaManager(
        mgr_a, rank=0, peers=[(1, '127.0.0.1', rm_b.server.port)],
        port=0, scrub_seconds=0, resync=False, **rm_a_kw)
    mgr_a.attach_replication(rm_a)
    mgr_b.attach_replication(rm_b)
    return mgr_a, mgr_b, rm_a, rm_b


def _hosted_dir(mgr_b):
    return os.path.join(mgr_b.directory, mf.REPLICA_SUBDIR, 'rank0')


def _payload_file(mgr, step):
    return os.path.join(mgr.step_dir(step), 'arrays', 'a00000.nd')


def _flip_byte(path, offset=None):
    with open(path, 'r+b') as f:
        data = f.read()
        off = len(data) // 2 if offset is None else offset
        f.seek(off)
        f.write(bytes([data[off] ^ 0x01]))


# ---------------------------------------------------------------------------
# replication push
# ---------------------------------------------------------------------------

def test_replication_pushes_committed_steps_to_peer(tmp_path):
    mgr_a, mgr_b, rm_a, rm_b = _pair(tmp_path)
    try:
        mgr_a.save(1, params=PARAMS, block=True)
        mgr_a.save(2, params=PARAMS, block=True)
        assert rm_a.wait(20)
        hosted = _hosted_dir(mgr_b)
        assert mf.committed_steps(hosted) == [1, 2]
        for s in (1, 2):
            mf.validate_step_dir(
                os.path.join(hosted, mf.step_dir_name(s)))
        # the replica is BIT-identical to the local commit
        for rel in ('manifest.json', 'arrays/a00000.nd'):
            with open(os.path.join(mgr_a.step_dir(2), rel), 'rb') as f1, \
                    open(os.path.join(hosted, mf.step_dir_name(2), rel),
                         'rb') as f2:
                assert f1.read() == f2.read()
        inv = dist.replica_inventory('127.0.0.1', rm_b.server.port)
        assert inv['hosted'] == {'rank0': [1, 2]}
        assert inv['local'] == []
    finally:
        mgr_a.close()
        mgr_b.close()


def test_slow_or_dead_peer_never_stalls_commit(tmp_path):
    """Acceptance: replication is fully off the training thread — a
    black-hole peer (accepts nothing, the connect queues in the listen
    backlog and every read times out) costs the push worker one bounded
    timeout per attempt, while save() returns at local-commit speed and
    restore stays local-fast."""
    hole = socket.socket()
    hole.bind(('127.0.0.1', 0))
    hole.listen(0)          # never accepted: reads time out client-side
    try:
        mgr_a = CheckpointManager(str(tmp_path / 'a'), async_save=False,
                                  replication=False)
        rm_a = ReplicaManager(
            mgr_a, rank=0, peers=[(1, '127.0.0.1',
                                   hole.getsockname()[1])],
            port=0, scrub_seconds=0, resync=False, timeout=0.3)
        mgr_a.attach_replication(rm_a)
        t0 = time.perf_counter()
        mgr_a.save(1, params=PARAMS, block=True)
        save_wall = time.perf_counter() - t0
        assert save_wall < 0.25, \
            f"save() waited on the dead peer ({save_wall:.3f}s)"
        assert rm_a.wait(30), "push worker wedged on the dead peer"
        assert rm_a.push_failures >= 1
        # restore is untouched by the dead peer: local copy is intact
        t0 = time.perf_counter()
        ck = mgr_a.restore_latest(apply=False)
        assert ck.step == 1
        assert time.perf_counter() - t0 < 1.0
        mgr_a.close()
    finally:
        hole.close()


def test_hang_injected_transfer_never_stalls_commit(tmp_path, monkeypatch):
    """Acceptance: dist.file_put:hang stalls the TRANSFER (push worker),
    not the training thread — save() returns immediately and the queue
    still drains once the hang elapses."""
    monkeypatch.setenv('MXTPU_FAULT_HANG_SECONDS', '0.4')
    mgr_a, mgr_b, rm_a, rm_b = _pair(tmp_path)
    try:
        faults.arm('dist.file_put', 'hang', window=(1, 1))
        t0 = time.perf_counter()
        mgr_a.save(1, params=PARAMS, block=True)
        assert time.perf_counter() - t0 < 0.3, \
            "save() waited on the hung transfer"
        assert rm_a.wait(30)
        assert mf.committed_steps(_hosted_dir(mgr_b)) == [1]
    finally:
        mgr_a.close()
        mgr_b.close()


def test_file_put_fault_raise_is_retried(tmp_path):
    """dist.file_put:raise on the first transfer occurrence: the push
    worker's bounded retry restages the step from scratch and the
    replica still lands."""
    mgr_a, mgr_b, rm_a, rm_b = _pair(tmp_path)
    try:
        faults.arm('dist.file_put', 'raise', window=(1, 1))
        mgr_a.save(1, params=PARAMS, block=True)
        assert rm_a.wait(20)
        assert mf.committed_steps(_hosted_dir(mgr_b)) == [1]
        assert faults.active()['dist.file_put']['fired'] == 1
    finally:
        mgr_a.close()
        mgr_b.close()


def test_file_put_fault_corrupt_is_rejected_then_retried(tmp_path):
    """dist.file_put:corrupt mangles the bytes in flight: the receiver's
    transfer hash check rejects them (no corrupt replica is ever
    staged as valid) and the retry delivers clean bytes."""
    mgr_a, mgr_b, rm_a, rm_b = _pair(tmp_path)
    try:
        faults.arm('dist.file_put', 'corrupt', window=(1, 1))
        mgr_a.save(1, params=PARAMS, block=True)
        assert rm_a.wait(20)
        hosted = _hosted_dir(mgr_b)
        assert mf.committed_steps(hosted) == [1]
        mf.validate_step_dir(os.path.join(hosted, mf.step_dir_name(1)))
    finally:
        mgr_a.close()
        mgr_b.close()


# ---------------------------------------------------------------------------
# any-replica restore
# ---------------------------------------------------------------------------

def test_restore_latest_falls_back_to_replica_when_local_wiped(tmp_path):
    mgr_a, mgr_b, rm_a, rm_b = _pair(tmp_path)
    try:
        mgr_a.save(1, params=PARAMS, block=True)
        mgr_a.save(2, params=PARAMS, block=True)
        assert rm_a.wait(20)
        for s in mgr_a.all_steps():
            shutil.rmtree(mgr_a.step_dir(s))
        ck = mgr_a.restore_latest(apply=False)
        assert ck.step == 2
        onp.testing.assert_array_equal(ck.params['w'], PARAMS['w'])
        assert mgr_a.last_restore_source == 'peer:rank1/rank0'
        # the fetch COMMITTED the step locally (hash-verified) — the
        # next restore needs no peer at all
        assert mgr_a.all_steps() == [2]
        mf.validate_step_dir(mgr_a.step_dir(2))
    finally:
        mgr_a.close()
        mgr_b.close()


def test_restore_repairs_corrupt_newest_from_replica(tmp_path):
    """A corrupt NEWEST local step is quarantined and repaired from the
    replica before falling back to the older local step — the restore
    resumes from the newest intact copy anywhere, not the newest local
    one."""
    telemetry.enable()
    telemetry.reset()
    mgr_a, mgr_b, rm_a, rm_b = _pair(tmp_path)
    try:
        mgr_a.save(1, params=PARAMS, block=True)
        p2 = {k: v + 1 for k, v in PARAMS.items()}
        mgr_a.save(2, params=p2, block=True)
        assert rm_a.wait(20)
        _flip_byte(_payload_file(mgr_a, 2))
        with pytest.warns(RuntimeWarning, match='repairing from a'):
            ck = mgr_a.restore_latest(apply=False)
        assert ck.step == 2, "fell back instead of repairing"
        onp.testing.assert_array_equal(ck.params['w'], p2['w'])
        assert telemetry.value(
            'mxnet_tpu_checkpoint_replica_fetches_total') == 1
        mf.validate_step_dir(mgr_a.step_dir(2))
    finally:
        mgr_a.close()
        mgr_b.close()
        telemetry.disable()
        telemetry.reset()


def test_checkpoint_read_fault_corrupt_falls_back_without_replica(tmp_path):
    """The checkpoint.read fault site: 'corrupt' on the first restore
    read mangles the bytes after the disk read, so the hash check fails
    and restore_latest falls back to the previous committed step — the
    corrupt-restore drill with no hand-flipped bytes."""
    mgr = CheckpointManager(str(tmp_path), async_save=False,
                            replication=False)
    mgr.save(1, params=PARAMS, block=True)
    p2 = {k: v + 1 for k, v in PARAMS.items()}
    mgr.save(2, params=p2, block=True)
    faults.arm('checkpoint.read', 'corrupt', window=(1, 1))
    with pytest.warns(RuntimeWarning, match='falling back'):
        ck = mgr.restore_latest(apply=False)
    assert ck.step == 1
    onp.testing.assert_array_equal(ck.params['w'], PARAMS['w'])
    mgr.close()


def test_checkpoint_read_fault_with_replica_repairs_newest(tmp_path):
    """Same drill with replication attached: the injected read
    corruption triggers a repair fetch and the restore still lands on
    the NEWEST step."""
    mgr_a, mgr_b, rm_a, rm_b = _pair(tmp_path)
    try:
        mgr_a.save(1, params=PARAMS, block=True)
        p2 = {k: v + 1 for k, v in PARAMS.items()}
        mgr_a.save(2, params=p2, block=True)
        assert rm_a.wait(20)
        faults.arm('checkpoint.read', 'corrupt', window=(1, 1))
        with pytest.warns(RuntimeWarning, match='repairing from a'):
            ck = mgr_a.restore_latest(apply=False)
        assert ck.step == 2
        onp.testing.assert_array_equal(ck.params['w'], p2['w'])
    finally:
        mgr_a.close()
        mgr_b.close()


# ---------------------------------------------------------------------------
# scrubber
# ---------------------------------------------------------------------------

def test_scrubber_detects_quarantines_and_repairs_bit_identical(tmp_path):
    """Acceptance: the scrubber detects an injected bit flip in a
    committed step, quarantines the corrupt copy and repairs it
    BIT-identical from the peer replica."""
    telemetry.enable()
    telemetry.reset()
    mgr_a, mgr_b, rm_a, rm_b = _pair(tmp_path)
    try:
        mgr_a.save(1, params=PARAMS, block=True)
        assert rm_a.wait(20)
        f = _payload_file(mgr_a, 1)
        with open(f, 'rb') as fh:
            pre = fh.read()
        _flip_byte(f)
        summary = rm_a.scrub_once()
        assert summary['corrupt'] == 1 and summary['repaired'] == 1
        with open(f, 'rb') as fh:
            assert fh.read() == pre, "repair is not bit-identical"
        qs = mf.quarantined_dirs(mgr_a.directory)
        assert len(qs) == 1 and qs[0][1] == 1
        assert telemetry.value(
            'mxnet_tpu_checkpoint_scrub_corrupt_total') == 1
        assert telemetry.value(
            'mxnet_tpu_checkpoint_scrub_repaired_total') == 1
        # a second pass over the repaired tree is clean
        s2 = rm_a.scrub_once()
        assert s2['corrupt'] == 0 and s2['local_checked'] == 1
    finally:
        mgr_a.close()
        mgr_b.close()
        telemetry.disable()
        telemetry.reset()


def test_scrubber_checkpoint_read_fault_site(tmp_path):
    """checkpoint.read:corrupt at scrub time: the scrubber's re-hash
    sees mangled bytes, quarantines the (actually fine) step and
    repairs it from the replica — the scrub drill needs no real
    bit-rot."""
    mgr_a, mgr_b, rm_a, rm_b = _pair(tmp_path)
    try:
        mgr_a.save(1, params=PARAMS, block=True)
        assert rm_a.wait(20)
        faults.arm('checkpoint.read', 'corrupt', window=(1, 1))
        summary = rm_a.scrub_once()
        assert summary['corrupt'] == 1 and summary['repaired'] == 1
        mf.validate_step_dir(mgr_a.step_dir(1))
    finally:
        mgr_a.close()
        mgr_b.close()


def test_scrubber_repairs_hosted_replica_from_owner(tmp_path):
    """Bit-rot in a HOSTED replica: the host's scrubber re-fetches it
    bit-identical from the owner's local copy."""
    mgr_a, mgr_b, rm_a, rm_b = _pair(tmp_path)
    try:
        mgr_a.save(1, params=PARAMS, block=True)
        assert rm_a.wait(20)
        rm_b._peers = [ReplicaPeer(0, '127.0.0.1', rm_a.server.port)]
        hf = os.path.join(_hosted_dir(mgr_b), mf.step_dir_name(1),
                          'arrays', 'a00000.nd')
        with open(_payload_file(mgr_a, 1), 'rb') as fh:
            pre = fh.read()
        _flip_byte(hf)
        summary = rm_b.scrub_once()
        assert summary['corrupt'] == 1 and summary['repaired'] == 1
        with open(hf, 'rb') as fh:
            assert fh.read() == pre
    finally:
        mgr_a.close()
        mgr_b.close()


# ---------------------------------------------------------------------------
# retention / GC
# ---------------------------------------------------------------------------

def test_retention_gc_retires_peer_replicas(tmp_path):
    """keep_last_n GC must also retire the steps' peer-hosted replicas
    (counted in mxnet_tpu_checkpoint_replica_gc_total) — replicas can't
    grow unboundedly."""
    telemetry.enable()
    telemetry.reset()
    mgr_a = CheckpointManager(str(tmp_path / 'a'), async_save=False,
                              keep_last_n=2, replication=False)
    mgr_b = CheckpointManager(str(tmp_path / 'b'), async_save=False,
                              replication=False)
    rm_b = ReplicaManager(mgr_b, rank=1, peers=[], port=0,
                          scrub_seconds=0, resync=False)
    rm_a = ReplicaManager(
        mgr_a, rank=0, peers=[(1, '127.0.0.1', rm_b.server.port)],
        port=0, scrub_seconds=0, resync=False)
    mgr_a.attach_replication(rm_a)
    mgr_b.attach_replication(rm_b)
    try:
        for s in range(1, 6):
            mgr_a.save(s, params=PARAMS, block=True)
        assert rm_a.wait(30)
        assert mgr_a.all_steps() == [4, 5]
        assert mf.committed_steps(_hosted_dir(mgr_b)) == [4, 5]
        assert rm_b.server.gc_total >= 3
        assert telemetry.value(
            'mxnet_tpu_checkpoint_replica_gc_total') >= 3
    finally:
        mgr_a.close()
        mgr_b.close()
        telemetry.disable()
        telemetry.reset()


def test_orphaned_replicas_gc_on_scrub_but_only_when_owner_has_newer(
        tmp_path):
    """A hosted replica whose owner retired it while this host was down
    is orphaned — GC'd by the next scrub pass. But when the owner has
    NO committed steps at all (it lost its disk), hosted replicas are
    precious and must never be treated as orphans."""
    mgr_a, mgr_b, rm_a, rm_b = _pair(tmp_path)
    try:
        mgr_a.save(5, params=PARAMS, block=True)
        assert rm_a.wait(20)
        rm_b._peers = [ReplicaPeer(0, '127.0.0.1', rm_a.server.port)]
        # fabricate an orphan: a hosted step the owner no longer has
        hosted = _hosted_dir(mgr_b)
        shutil.copytree(os.path.join(hosted, mf.step_dir_name(5)),
                        os.path.join(hosted, mf.step_dir_name(1)))
        summary = rm_b.scrub_once()
        assert summary['orphans_gc'] == 1
        assert mf.committed_steps(hosted) == [5]
        # owner loses its disk entirely: nothing is orphaned anymore
        shutil.rmtree(mgr_a.step_dir(5))
        shutil.copytree(os.path.join(hosted, mf.step_dir_name(5)),
                        os.path.join(hosted, mf.step_dir_name(1)))
        summary = rm_b.scrub_once()
        assert summary['orphans_gc'] == 0
        assert mf.committed_steps(hosted) == [1, 5]
    finally:
        mgr_a.close()
        mgr_b.close()


def test_quarantine_expiry_honors_keep_every_k(tmp_path):
    """Quarantined copies expire when their STEP leaves retention —
    including under keep_every_k_steps, where the oldest pinned step
    would defeat any min-step cutoff."""
    mgr = CheckpointManager(str(tmp_path), async_save=False,
                            keep_last_n=2, keep_every_k_steps=100,
                            replication=False)
    mgr.save(100, params=PARAMS, block=True)   # pinned forever by k=100
    mgr.save(101, params=PARAMS, block=True)
    # fabricate quarantines: one for a long-expired step, one for a
    # retained step
    for s in (5, 101):
        q = mgr.step_dir(s) + f'.quarantine-{os.getpid()}'
        os.makedirs(os.path.join(q, 'arrays'))
    mgr.save(102, params=PARAMS, block=True)   # triggers _gc
    left = {s for _p, s in mf.quarantined_dirs(mgr.directory)}
    assert left == {101}, left                 # expired evidence swept
    mgr.close()


def test_fetch_rejects_traversal_paths_in_replica_manifest(tmp_path):
    """A corrupt (or hostile) replica manifest naming '../...' payload
    paths must never write outside the fetch staging dir — the fetch of
    that step fails and the restore falls back to the next intact
    replica step."""
    import json
    mgr_a, mgr_b, rm_a, rm_b = _pair(tmp_path)
    try:
        mgr_a.save(1, params=PARAMS, block=True)
        mgr_a.save(2, params=PARAMS, block=True)
        assert rm_a.wait(20)
        # poison the hosted replica of step 2: its manifest now names a
        # payload path that, joined naively, would land in step 1's dir
        hdir = os.path.join(_hosted_dir(mgr_b), mf.step_dir_name(2))
        with open(os.path.join(hdir, mf.MANIFEST_NAME)) as f:
            doc = json.load(f)
        doc['arrays'][0]['file'] = '../step_0000000001/manifest.json'
        with open(os.path.join(hdir, mf.MANIFEST_NAME), 'w') as f:
            json.dump(doc, f)
        # wipe ALL local steps: the any-replica restore must reject the
        # poisoned step-2 replica and land on the clean step-1 replica
        for s in mgr_a.all_steps():
            shutil.rmtree(mgr_a.step_dir(s))
        ck = mgr_a.restore_latest(apply=False)
        assert ck.step == 1, "poisoned replica was not rejected"
        onp.testing.assert_array_equal(ck.params['w'], PARAMS['w'])
        # nothing escaped: the only local artifacts are step 1 and its
        # (validated) contents
        assert mgr_a.all_steps() == [1]
        mf.validate_step_dir(mgr_a.step_dir(1))
    finally:
        mgr_a.close()
        mgr_b.close()


# ---------------------------------------------------------------------------
# crash matrix
# ---------------------------------------------------------------------------

def test_receiver_kill9_mid_transfer_leaves_no_partial_replica(tmp_path):
    """Acceptance: kill -9 the RECEIVER mid-transfer — no partial
    replica is ever visible (only uncommitted staging, swept on
    restart), and the next replication to a fresh server over the same
    root succeeds."""
    root = str(tmp_path / 'replicas')
    port = None
    with socket.socket() as s:
        s.bind(('', 0))
        port = s.getsockname()[1]
    env = dict(os.environ, JAX_PLATFORMS='cpu')

    def spawn():
        p = subprocess.Popen(
            [sys.executable, '-m', 'mxnet_tpu.checkpoint.replica',
             '--serve', '--root', root, '--port', str(port)],
            env=env, cwd=REPO, stdout=subprocess.PIPE, text=True)
        assert p.stdout.readline().strip() == 'ready'
        return p

    server = spawn()
    try:
        # a real committed step to replicate
        mgr = CheckpointManager(str(tmp_path / 'local'),
                                async_save=False, replication=False)
        big = {'w': onp.random.RandomState(0)
               .randn(512, 512).astype(onp.float32)}
        mgr.save(1, params=big, block=True)
        doc = mf.read_manifest(mgr.step_dir(1))
        rels = [e['file'] for e in doc['arrays'] + doc['blobs']]

        # start a bandwidth-paced put (1 MB/s over ~1 MB) and SIGKILL
        # the server mid-transfer
        errs = []

        def slow_put():
            rel = rels[0]
            with open(os.path.join(mgr.step_dir(1), rel), 'rb') as f:
                data = f.read()
            try:
                dist.file_put('127.0.0.1', port, 'rank0', 1, rel, data,
                              timeout=10.0, bandwidth_mbps=0.4)
            except MXNetError as e:
                errs.append(e)

        t = threading.Thread(target=slow_put)
        t.start()
        time.sleep(0.5)                      # mid-transfer
        server.send_signal(signal.SIGKILL)
        server.wait()
        t.join(20.0)
        assert errs, "the interrupted transfer did not surface an error"
        # no partial replica visible: no committed step dir anywhere
        nsdir = os.path.join(root, 'rank0')
        assert mf.committed_steps(nsdir) == []

        # restart over the same root: stale staging swept, and a full
        # push + commit succeeds
        server = spawn()
        for rel in rels + [mf.MANIFEST_NAME]:
            with open(os.path.join(mgr.step_dir(1), rel), 'rb') as f:
                dist.file_put('127.0.0.1', port, 'rank0', 1, rel,
                              f.read(), timeout=10.0)
        dist.replica_commit('127.0.0.1', port, 'rank0', 1, timeout=10.0)
        assert mf.committed_steps(nsdir) == [1]
        mf.validate_step_dir(os.path.join(nsdir, mf.step_dir_name(1)))
        assert not mf.stale_tmp_dirs(nsdir), \
            "restart did not sweep the dead transfer's staging"
        mgr.close()
    finally:
        if server.poll() is None:
            server.kill()
            server.wait()


_SENDER_KILL9 = r"""
import os, signal, sys
sys.path.insert(0, os.getcwd())
import numpy as onp
from mxnet_tpu.checkpoint import CheckpointManager, ReplicaManager
root, hole_port = sys.argv[1], int(sys.argv[2])
mgr = CheckpointManager(root, async_save=False, replication=False)
# replication target is a black-hole: the push is still PENDING when
# the kill lands — exactly "between local commit and replication"
rm = ReplicaManager(mgr, rank=0, peers=[(1, '127.0.0.1', hole_port)],
                    port=0, scrub_seconds=0, resync=False, timeout=30.0)
mgr.attach_replication(rm)
params = {'w': onp.arange(12, dtype=onp.float32).reshape(3, 4)}
mgr.save(1, params=params, block=True)
assert os.path.isdir(os.path.join(root, 'step_0000000001'))
print('COMMITTED', flush=True)
os.kill(os.getpid(), signal.SIGKILL)
print('UNREACHABLE')
"""


def test_sender_kill9_after_commit_resumes_replication_on_restart(
        tmp_path):
    """Acceptance: kill -9 the SENDER between local commit and
    replication — the local restore is unaffected, and a restarted
    manager's resync pass pushes the missing step to the peer."""
    root = str(tmp_path / 'a')
    hole = socket.socket()
    hole.bind(('127.0.0.1', 0))
    hole.listen(0)
    env = dict(os.environ, JAX_PLATFORMS='cpu')
    try:
        res = subprocess.run(
            [sys.executable, '-c', _SENDER_KILL9, root,
             str(hole.getsockname()[1])],
            capture_output=True, text=True, env=env, cwd=REPO,
            timeout=600)
        assert res.returncode == -signal.SIGKILL, (res.returncode,
                                                   res.stderr)
        assert 'COMMITTED' in res.stdout
        assert 'UNREACHABLE' not in res.stdout
    finally:
        hole.close()

    # local restore unaffected
    mgr_a = CheckpointManager(root, replication=False)
    ck = mgr_a.restore_latest(apply=False)
    assert ck.step == 1
    onp.testing.assert_array_equal(
        ck.params['w'], onp.arange(12, dtype=onp.float32).reshape(3, 4))

    # "restart": a live peer + a fresh ReplicaManager with resync=True
    # pushes the committed-but-never-replicated step
    mgr_b = CheckpointManager(str(tmp_path / 'b'), async_save=False,
                              replication=False)
    rm_b = ReplicaManager(mgr_b, rank=1, peers=[], port=0,
                          scrub_seconds=0, resync=False)
    mgr_b.attach_replication(rm_b)
    rm_a = ReplicaManager(
        mgr_a, rank=0, peers=[(1, '127.0.0.1', rm_b.server.port)],
        port=0, scrub_seconds=0, resync=True)
    mgr_a.attach_replication(rm_a)
    try:
        assert rm_a.wait(30)
        assert mf.committed_steps(_hosted_dir(mgr_b)) == [1]
    finally:
        mgr_a.close()
        mgr_b.close()


# ---------------------------------------------------------------------------
# watchdog verdict / auto wiring / CLI
# ---------------------------------------------------------------------------

def test_stall_verdict_peer_loss_suspected_during_replica_fetch():
    from mxnet_tpu.checkpoint import replica as replica_mod

    class _Ms:
        rank = 0
        deadline_seconds = 1.0

        def lost_peers(self):
            return []

        def peer_ages(self):
            return {1: 0.1}

    # all peers beating, no fetch: local stall
    v = stall_verdict(_Ms())
    assert v['verdict'] == 'local_stall' and 'during' not in v
    with replica_mod._fetching():
        # a fetch in flight flips the verdict: the serving peer is the
        # prime suspect even though it still heartbeats
        v = stall_verdict(_Ms())
        assert v['verdict'] == 'peer_loss_suspected'
        assert v['during'] == 'replica_fetch'
        # ...even with no membership at all
        v = stall_verdict(None) if dist.membership() is None else None
        if v is not None:
            assert v['verdict'] == 'peer_loss_suspected'
            assert v['during'] == 'replica_fetch'
    assert stall_verdict(None) is None or dist.membership() is not None


def test_watchdog_report_names_replica_fetch(tmp_path):
    from mxnet_tpu.resilience.watchdog import StepWatchdog
    from mxnet_tpu.checkpoint import replica as replica_mod

    class _Ms:
        rank = 0
        deadline_seconds = 1.0

        def lost_peers(self):
            return []

        def peer_ages(self):
            return {1: 0.1}

    wd = StepWatchdog(deadline_seconds=60, membership=_Ms())
    with replica_mod._fetching():
        report = wd._format_report(61.0, 7)
    assert 'PEER LOSS SUSPECTED (during replica fetch)' in report
    report = wd._format_report(61.0, 7)
    assert 'LOCAL STALL' in report


def test_manager_auto_attaches_replication_from_membership(
        tmp_path, monkeypatch):
    """The production wiring: MXTPU_CHECKPOINT_REPLICAS > 0 + a running
    membership world > 1 auto-attaches a ReplicaManager serving on
    MXTPU_REPLICA_PORT_BASE + rank."""
    from mxnet_tpu.resilience.drill import _free_port_base
    base = _free_port_base(1)
    monkeypatch.setenv('MXTPU_REPLICA_PORT_BASE', str(base))
    monkeypatch.setenv('MXTPU_CHECKPOINT_REPLICAS', '1')
    ms = dist.Membership(0, 2, port=_free_port_base(1),
                         heartbeat_seconds=0.05, deadline_seconds=5.0)
    monkeypatch.setattr(dist, '_membership', ms)
    try:
        mgr = CheckpointManager(str(tmp_path))
        assert mgr.replica is not None
        assert mgr.replica.rank == 0 and mgr.replica.ns == 'rank0'
        assert mgr.replica.server.port == base
        mgr.close()
        assert mgr.replica is None
        # replication=False forces it off even with the env set
        mgr2 = CheckpointManager(str(tmp_path), replication=False)
        assert mgr2.replica is None
        mgr2.close()
    finally:
        ms.stop()


def test_manifest_cli_scrub_exit_codes(tmp_path):
    """tools/check_checkpoint_manifest.py --scrub deep-verifies local
    steps AND hosted replicas with distinct exit codes: 0 clean, 2
    corrupt, 3 missing."""
    mgr_a, mgr_b, rm_a, rm_b = _pair(tmp_path)
    tool = os.path.join(REPO, 'tools', 'check_checkpoint_manifest.py')

    def run(path):
        return subprocess.run(
            [sys.executable, tool, path, '--scrub'],
            capture_output=True, text=True).returncode

    try:
        # a wiped/empty root must NOT pass the deep scan as clean
        empty = str(tmp_path / 'wiped')
        os.makedirs(empty)
        assert run(empty) == 3
        mgr_a.save(1, params=PARAMS, block=True)
        mgr_a.save(2, params=PARAMS, block=True)
        assert rm_a.wait(20)
        assert run(mgr_a.directory) == 0
        assert run(mgr_b.directory) == 0      # hosted replicas scanned
        # corrupt: hash mismatch in a HOSTED replica -> 2
        _flip_byte(os.path.join(_hosted_dir(mgr_b), mf.step_dir_name(1),
                                'arrays', 'a00000.nd'))
        assert run(mgr_b.directory) == 2
        # missing payload file -> 3
        os.unlink(_payload_file(mgr_a, 2))
        assert run(mgr_a.directory) == 3
        # corrupt dominates a mixed tree -> 2
        _flip_byte(_payload_file(mgr_a, 1))
        assert run(mgr_a.directory) == 2
    finally:
        mgr_a.close()
        mgr_b.close()


# ---------------------------------------------------------------------------
# the e2e disk-loss drill
# ---------------------------------------------------------------------------

@pytest.mark.slow  # duplicated by the dryrun_multichip disk-loss stage
def test_disk_loss_drill_survivor_restores_from_replica(tmp_path):
    """Acceptance: two-worker drill with the checkpoint OWNER's
    directory wiped before its SIGKILL — the survivor restores from the
    replica it hosts (run_drill asserts the source and that the fetched
    step is bit-identical to the hosted copy) and its post-re-form
    trajectory is bit-identical to a clean local restore."""
    from mxnet_tpu.resilience.drill import run_drill
    result = run_drill(str(tmp_path), disk_loss=True)
    assert result['ok'] and result['bit_identical']
    assert result['restore_source'].startswith('hosted:rank1')
    assert result['post_steps'] >= 1
    assert 0 < result['mttr']['detect_seconds'] < 10
    assert result['mttr']['total_seconds'] < 20
