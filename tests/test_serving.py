"""Production inference serving (ISSUE 17): continuous batching onto a
fixed bucket grid, AOT warmup through the persistent compile cache,
replica server + fleet router, and the zero-recompile steady state.

The drill test at the bottom is the two-process acceptance path:
SIGTERM one replica mid-storm -> zero failed requests, router ejects
via the membership departure, weight push lands on the survivor."""
import glob
import os
import threading
import time
import warnings

import numpy as onp
import pytest

import mxnet_tpu as mx
from mxnet_tpu import nd, serving, telemetry
from mxnet_tpu.base import MXNetError
from mxnet_tpu.gluon import nn
from mxnet_tpu.resilience import faults
from mxnet_tpu.serving.batcher import (batch_bucket_for, parse_buckets,
                                       seq_bucket_for)
from mxnet_tpu.telemetry import compile as comp, memory, metrics


@pytest.fixture(autouse=True)
def _telem():
    telemetry.reset()
    telemetry.enable()
    comp.enable()
    yield
    faults.disarm()
    metrics.set_recompile_threshold(None)
    comp.disable()
    comp.clear(ledger='', cache_dir='')
    telemetry.reset()
    telemetry.disable()


class TokModel(nn.HybridBlock):
    def __init__(self, vocab=64, dim=8, classes=4, **kw):
        super().__init__(**kw)
        with self.name_scope():
            self.embed = nn.Embedding(vocab, dim)
            self.proj = nn.Dense(classes, flatten=False)

    def forward(self, x):
        return self.proj(self.embed(x))


def _engine(**kw):
    net = TokModel()
    net.initialize()
    kw.setdefault('seq_buckets', '8,16')
    kw.setdefault('batch_buckets', '1,2,4')
    kw.setdefault('deadline_ms', 2.0)
    eng = serving.InferenceEngine(serving.BlockRunner(net), **kw)
    return net, eng


# ---------------------------------------------------------------------------
# bucketing helpers
# ---------------------------------------------------------------------------

def test_parse_buckets_sorts_and_dedupes():
    assert parse_buckets('128, 32,64,32') == (32, 64, 128)
    with pytest.raises(MXNetError):
        parse_buckets('')
    with pytest.raises(MXNetError):
        parse_buckets('0,8')


def test_bucket_selection_smallest_fit():
    assert seq_bucket_for(1, (32, 64)) == 32
    assert seq_bucket_for(32, (32, 64)) == 32
    assert seq_bucket_for(33, (32, 64)) == 64
    assert seq_bucket_for(65, (32, 64)) is None
    assert batch_bucket_for(3, (1, 2, 4)) == 4
    assert batch_bucket_for(4, (1, 2, 4)) == 4


def test_bucket_grid_is_the_full_universe_largest_first():
    _net, eng = _engine()
    grid = eng.bucket_grid()
    assert len(grid) == 2 * 3
    assert grid[0] == (4, 16)          # most expensive shape compiles first
    assert set(grid) == {(b, s) for s in (8, 16) for b in (1, 2, 4)}
    eng.drain()


# ---------------------------------------------------------------------------
# batch formation: deadline vs fill
# ---------------------------------------------------------------------------

def test_fill_dispatches_before_deadline():
    _net, eng = _engine(deadline_ms=2000.0, batch_buckets='1,4')
    serving.warmup(eng)
    t0 = time.monotonic()
    handles = [eng.submit_async([1, 2, 3]) for _ in range(4)]
    outs = [eng.result(h, timeout=10.0) for h in handles]
    took = time.monotonic() - t0
    assert all(o.shape == (3, 4) for o in outs)
    # a full batch must not wait for the 2-second deadline
    assert took < 1.0, took
    eng.drain()


def test_deadline_dispatches_a_lone_request():
    _net, eng = _engine(deadline_ms=300.0, batch_buckets='4')
    serving.warmup(eng)
    t0 = time.monotonic()
    out = eng.submit([1, 2, 3], timeout=10.0)
    took = time.monotonic() - t0
    assert out.shape == (3, 4)
    # a lone request rides the deadline, not the fill
    assert took >= 0.25, took
    eng.drain()


# ---------------------------------------------------------------------------
# padding parity + zero-recompile storm
# ---------------------------------------------------------------------------

def test_padding_parity_bit_identical():
    net, eng = _engine()
    serving.warmup(eng)
    seq = [5, 9, 2, 41, 7]
    out = eng.submit(seq, timeout=10.0)
    padded = onp.asarray([seq + [0] * 3], 'int32')
    solo = onp.asarray(net(nd.array(padded)).asnumpy())[0, :5]
    assert out.shape == (5, 4)
    assert onp.array_equal(out, solo), (out, solo)
    eng.drain()


def test_zero_recompiles_after_warmup_randomized_storm():
    _net, eng = _engine()
    rep = serving.warmup(eng)
    assert rep['compiles'] and rep['compiles'] > 0
    n_led = len(comp.ledger())
    rng = onp.random.RandomState(3)
    errs = []

    def client():
        try:
            length = int(rng.randint(1, 17))
            out = eng.submit(list(rng.randint(0, 64, length)),
                             timeout=30.0)
            assert out.shape == (length, 4)
        except Exception as e:                        # noqa: BLE001
            errs.append(e)

    with warnings.catch_warnings(record=True) as caught:
        warnings.simplefilter('always')
        threads = [threading.Thread(target=client) for _ in range(40)]
        for t in threads:
            t.start()
        for t in threads:
            t.join(timeout=60)
    assert not errs, errs
    recompiled = [w for w in caught
                  if 'Recompile' in type(w.message).__name__]
    assert not recompiled, [str(w.message) for w in recompiled]
    assert len(comp.ledger()) == n_led, \
        f"storm recompiled: {comp.ledger()[n_led:]}"
    st = eng.stats()
    assert st['requests'] == 40 and st['shed'] == 0
    assert st['p50_ms'] is not None and st['p99_ms'] >= st['p50_ms']
    eng.drain()


def test_warmup_report_and_threshold_restore():
    _net, eng = _engine()
    metrics.set_recompile_threshold(5)
    rep = serving.warmup(eng)
    # the warmup pass mutes the detector, then restores the caller's
    # threshold — warmup compiling the whole grid is the point
    assert metrics._recompile_threshold == 5
    assert set(rep['buckets']) == {f'b{b}_s{s}'
                                   for b, s in eng.bucket_grid()}
    assert rep['total_seconds'] > 0
    assert telemetry.value('mxnet_tpu_serving_warmup_buckets',
                           engine=eng.name) == 6
    eng.drain()


# ---------------------------------------------------------------------------
# shedding: OOM guard, admission control, queue limit, oversized
# ---------------------------------------------------------------------------

def test_oom_sheds_batch_and_replica_survives(tmp_path, monkeypatch):
    monkeypatch.setenv('MXTPU_FLIGHT_DIR', str(tmp_path))
    memory.enable()
    _net, eng = _engine()
    serving.warmup(eng)
    faults.arm('alloc.oom', 'raise', window=1)
    with pytest.raises(serving.RequestShed):
        eng.submit([1, 2, 3], timeout=10.0)
    faults.disarm()
    # the replica survives the burst: the next request serves
    out = eng.submit([1, 2, 3], timeout=10.0)
    assert out.shape == (3, 4)
    assert eng.stats()['shed'] >= 1
    eng.drain()


def test_admission_control_sheds_before_the_device():
    _net, eng = _engine(admission=lambda: 'memory_pressure')
    with pytest.raises(serving.RequestShed, match='memory_pressure'):
        eng.submit([1, 2, 3])
    assert eng.stats()['shed'] == 1
    eng.drain()


def test_queue_limit_sheds():
    _net, eng = _engine(queue_limit=1, deadline_ms=5000.0,
                        batch_buckets='4')
    eng.submit_async([1, 2, 3])          # parks waiting for fill
    with pytest.raises(serving.RequestShed, match='queue full'):
        eng.submit_async([4, 5])
    eng.drain()


def test_too_long_request_is_a_client_error():
    _net, eng = _engine()
    with pytest.raises(serving.RequestTooLarge):
        eng.submit(list(range(17)))
    eng.drain()


def test_memory_admission_predicate(monkeypatch):
    assert serving.memory_admission(0) is None
    admit = serving.memory_admission(1.0)    # 1 MiB limit
    monkeypatch.setattr(memory, 'health_fields',
                        lambda: {'live_bytes': 8 << 20})
    assert 'memory_pressure' in admit()
    monkeypatch.setattr(memory, 'health_fields',
                        lambda: {'live_bytes': 0})
    assert admit() is None


# ---------------------------------------------------------------------------
# weight quantization
# ---------------------------------------------------------------------------

def _tok_model():
    net = TokModel()
    net.initialize()
    net(nd.array(onp.zeros((1, 8), 'int32')))   # materialize deferred params
    return net


def test_quantize_weights_bf16_and_int8():
    net = _tok_model()
    serving.quantize_weights(net, 'bf16')
    assert str(net.proj.weight.data().dtype) == 'bfloat16'
    net2 = _tok_model()
    before = onp.asarray(net2.proj.weight.data().asnumpy()).copy()
    serving.quantize_weights(net2, 'int8')
    after = onp.asarray(net2.proj.weight.data().asnumpy())
    assert not onp.array_equal(before, after)       # snapped to the grid
    assert onp.allclose(before, after, atol=0.1)    # but nearby
    with pytest.raises(MXNetError):
        serving.quantize_weights(net2, 'fp4')
    assert serving.quantize_weights(net2, '') is net2


# ---------------------------------------------------------------------------
# replica server routes
# ---------------------------------------------------------------------------

@pytest.fixture()
def served():
    net, eng = _engine()
    serving.warmup(eng)
    srv = serving.PredictServer(eng, block=net)
    yield net, eng, srv
    srv.stop()
    eng.drain()


def test_predict_single_and_list(served):
    _net, _eng, srv = served
    st, doc = serving.http_json('127.0.0.1', srv.port, '/predict',
                                {'inputs': [1, 2, 3]})
    assert st == 200 and len(doc['outputs']) == 3
    assert doc['latency_ms'] > 0
    st, doc = serving.http_json('127.0.0.1', srv.port, '/predict',
                                {'inputs': [[1, 2, 3], [4, 5]]})
    assert st == 200
    assert len(doc['outputs']) == 2 and len(doc['outputs'][1]) == 2


def test_predict_client_errors(served):
    _net, _eng, srv = served
    st, doc = serving.http_json('127.0.0.1', srv.port, '/predict',
                                {'wrong_key': 1})
    assert st == 400, doc
    st, doc = serving.http_json('127.0.0.1', srv.port, '/predict',
                                {'inputs': list(range(99))})
    assert st == 400, doc
    st, _doc = serving.http_json('127.0.0.1', srv.port, '/nope', {})
    assert st == 404
    # the inherited GET routes still answer
    st, doc = serving.http_json('127.0.0.1', srv.port, '/healthz')
    assert st in (200, 503) and isinstance(doc, dict)


def test_reload_by_path_swaps_weights(served, tmp_path):
    net, _eng, srv = served
    donor = _tok_model()
    path = str(tmp_path / 'weights.params')
    donor.save_parameters(path)
    st, before = serving.http_json('127.0.0.1', srv.port, '/predict',
                                   {'inputs': [1, 2, 3]})
    assert st == 200
    st, doc = serving.http_json('127.0.0.1', srv.port, '/reload',
                                {'path': path})
    assert st == 200 and doc['reloaded'], doc
    st, after = serving.http_json('127.0.0.1', srv.port, '/predict',
                                  {'inputs': [1, 2, 3]})
    assert st == 200
    # the donor's weights differ, so the outputs must flip...
    assert before['outputs'] != after['outputs']
    # ...to exactly the donor's own forward (per-call param reads)
    want = onp.asarray(donor(nd.array(onp.asarray(
        [[1, 2, 3] + [0] * 5], 'int32'))).asnumpy())[0, :3]
    assert onp.allclose(onp.asarray(after['outputs']), want, atol=1e-6)


def test_reload_invalid_step_is_409(served, tmp_path):
    _net, _eng, srv = served
    srv.replica_root = str(tmp_path)
    st, doc = serving.http_json('127.0.0.1', srv.port, '/reload',
                                {'ns': 'serving', 'step': 3})
    assert st == 409, doc


def test_drain_stops_admission_and_listener(served):
    _net, eng, srv = served
    st, doc = serving.http_json('127.0.0.1', srv.port, '/drain', {})
    assert st == 200 and doc['draining']
    deadline = time.monotonic() + 10.0
    while srv._server is not None and time.monotonic() < deadline:
        time.sleep(0.02)
    assert srv._server is None, "drain never closed the listener"
    with pytest.raises(serving.RequestShed):
        eng.submit([1, 2, 3])


# ---------------------------------------------------------------------------
# router: failover, ejection, readmission
# ---------------------------------------------------------------------------

def _dead_port():
    import socket
    with socket.socket() as s:
        s.bind(('', 0))
        return s.getsockname()[1]


def test_router_fails_over_and_ejects(served):
    _net, _eng, srv = served
    dead = _dead_port()
    r = serving.Router(endpoints=[('127.0.0.1', dead),
                                  ('127.0.0.1', srv.port)],
                       eject_failures=1, readmit_seconds=60.0)
    outs = [r.predict([1, 2, 3]) for _ in range(4)]
    assert all(len(o) == 3 for o in outs)
    assert r.failovers >= 1
    assert 0 in r.ejected()              # the dead endpoint is out
    assert telemetry.value('mxnet_tpu_serving_ejections_total',
                           rank=0) >= 1


def test_router_4xx_is_the_callers_fault_no_ejection(served):
    _net, _eng, srv = served
    r = serving.Router(endpoints=[('127.0.0.1', srv.port)],
                       eject_failures=1)
    with pytest.raises(MXNetError):
        r.predict(list(range(99)))       # too long -> 400
    assert r.ejected() == []             # the replica keeps its seat


def test_router_no_replicas():
    r = serving.Router(endpoints=[])
    with pytest.raises(serving.NoReplicasError):
        r.predict([1, 2, 3])


# ---------------------------------------------------------------------------
# name-stable lowering (the PR 15 churn fix this PR roots out):
# differently-auto-named identical blocks share ONE persistent cache
# entry — gluon prefixes must never reach the compiled program key
# ---------------------------------------------------------------------------

def _cache_files(cache):
    return len([f for f in glob.glob(os.path.join(cache, '**'),
                                     recursive=True) if os.path.isfile(f)])


def test_cachedop_cache_key_is_prefix_free(tmp_path):
    cache = str(tmp_path / 'xla_cache')
    comp.clear(cache_dir=cache)
    x = nd.array(onp.random.randn(4, 8).astype('float32'))
    a = nn.Dense(16, in_units=8)
    a.initialize()
    a.hybridize()
    a(x)
    n1 = _cache_files(cache)
    b = nn.Dense(16, in_units=8)       # auto-naming bumps the prefix
    b.initialize()
    b.hybridize()
    assert b.name != a.name
    b(x)
    n2 = _cache_files(cache)
    assert n1 >= 1, "cache never wrote"
    assert n2 == n1, f"prefix churned the compiled-program key: {n1}->{n2}"


def test_train_step_cache_key_is_prefix_free(tmp_path):
    from mxnet_tpu.parallel import ShardedTrainStep
    cache = str(tmp_path / 'xla_cache')
    comp.clear(cache_dir=cache)
    # batch 8: the step shards over the conftest's 8-device CPU mesh
    x = nd.array(onp.random.randn(8, 8).astype('float32'))
    y = nd.array(onp.random.randn(8, 4).astype('float32'))

    def build():
        net = nn.Dense(4, in_units=8)
        net.initialize()
        net(x)
        return ShardedTrainStep(net, lambda o, t: (o - t) ** 2,
                                optimizer='sgd',
                                optimizer_params={'learning_rate': 0.01})

    s1 = build()
    s1(x, y)
    n1 = _cache_files(cache)
    s2 = build()                        # different auto prefix
    assert s2.block.name != s1.block.name
    s2(x, y)
    n2 = _cache_files(cache)
    assert n1 >= 1, "step never wrote the cache"
    assert n2 == n1, f"prefix churned the step program key: {n1}->{n2}"


# ---------------------------------------------------------------------------
# the two-process drain drill (acceptance path)
# ---------------------------------------------------------------------------

@pytest.mark.slow  # duplicated by the dryrun_multichip serving stage
def test_serving_drain_drill_two_replicas(tmp_path):
    """SIGTERM one replica mid-storm: zero failed requests (router
    fails over), the departure drops it from the membership-discovered
    set (MTTR measured), zero post-warmup recompiles on either replica,
    the second replica's warmup rides the first's persistent cache, and
    a weight push + /reload lands on the survivor."""
    from mxnet_tpu.resilience.drill import run_serving_drill
    out = run_serving_drill(str(tmp_path))
    assert out['ok'] and out['failed'] == 0
    assert out['mttr_seconds'] < 10.0
    assert out['warmup'][2]['cache']['hits'] > 0
    assert out['reloaded_step'] == 7
