"""ZeRO-1 sharded optimizer step (ISSUE 4): reduce-scatter grads,
shard-local AdamW, overlapped all-gather — parity vs the replicated
update on the 8-device CPU mesh, tp composition, layout-independent
checkpoints across dp degrees, and the comm telemetry contract."""
import os
import pickle

import numpy as onp
import pytest

import jax
from jax.sharding import NamedSharding, PartitionSpec as P

import mxnet_tpu as mx
from mxnet_tpu import nd, gluon, autograd, telemetry
from mxnet_tpu.base import MXNetError
from mxnet_tpu.gluon import nn
from mxnet_tpu.parallel import make_mesh, ShardedTrainStep
from mxnet_tpu.parallel.step import compose_zero_spec


def _data(n=64, din=16, classes=8, seed=0):
    rng = onp.random.RandomState(seed)
    x = rng.randn(n, din).astype(onp.float32)
    y = rng.randint(0, classes, n).astype(onp.float32)
    return nd.array(x), nd.array(y)


def _net(din=16, hidden=32, classes=8):
    mx.random.seed(0)
    net = nn.HybridSequential()
    net.add(nn.Dense(hidden, activation='relu', in_units=din))
    net.add(nn.Dense(classes, in_units=hidden))
    net.initialize(mx.init.Xavier())
    return net


def _run_step(optimizer, mesh, zero, steps=3, param_specs=None, net=None):
    net = net if net is not None else _net()
    loss_fn = gluon.loss.SoftmaxCrossEntropyLoss()
    step = ShardedTrainStep(net, loss_fn, optimizer,
                            {'learning_rate': 0.01}, mesh=mesh, zero=zero,
                            param_specs=param_specs)
    x, y = _data()
    losses = [float(step(x, y).asscalar()) for _ in range(steps)]
    return net, step, losses


# ---------------------------------------------------------------------------
# parity: ZeRO-1 must train the SAME model as the replicated update
# ---------------------------------------------------------------------------

@pytest.mark.parametrize('optimizer', ['adam', 'adamw', 'lamb'])
def test_zero1_parity_vs_replicated(optimizer):
    """dp=8: 3-step loss trajectory matches the replicated update to
    <=1e-6 in fp32 (acceptance criterion), and the updated weights agree
    too — the reduce-scatter/shard-update/all-gather decomposition is a
    pure layout change."""
    mesh = make_mesh((8,), ('dp',))
    net_z, step_z, loss_z = _run_step(optimizer, mesh, zero=True)
    net_r, step_r, loss_r = _run_step(optimizer, mesh, zero=False)
    assert step_z.zero and not step_r.zero
    for a, b in zip(loss_z, loss_r):
        assert abs(a - b) <= 1e-6, (optimizer, loss_z, loss_r)
    for (n, pz), (_, pr) in zip(sorted(net_z.collect_params().items()),
                                sorted(net_r.collect_params().items())):
        d = float(onp.max(onp.abs(pz.data().asnumpy()
                                  - pr.data().asnumpy())))
        assert d <= 1e-6, (optimizer, n, d)


def test_zero1_state_is_sharded_one_over_dp():
    """Every shardable state tensor carries the dp axis, and ONE device
    holds ~1/dp of the replicated optimizer-state bytes (± the
    replicated step-count scalars)."""
    mesh = make_mesh((8,), ('dp',))
    _, step_z, _ = _run_step('adamw', mesh, zero=True)
    _, step_r, _ = _run_step('adamw', mesh, zero=False)
    assert all(spec is not None and 'dp' in str(spec)
               for spec in step_z.zero_specs.values())
    for n, st in step_z._opt_state.items():
        for s in st:
            if s.ndim:
                assert not s.sharding.is_fully_replicated, n
    zb = step_z.opt_state_bytes_per_device()
    rb = step_r.opt_state_bytes_per_device()
    assert rb / 8 <= zb <= rb / 4, (zb, rb)


def test_zero1_composes_with_tp():
    """ZeRO + tp=2 (acceptance): a tp-sharded weight's optimizer state
    shards over BOTH axes — the dp shard composes onto a dim tp does not
    already claim — and the trajectory still matches zero-off on the
    same mesh."""
    mesh = make_mesh((4, 2), ('dp', 'tp'))

    def run(zero):
        net = _net()   # fresh net: specs keyed by ITS auto-generated name
        return _run_step('adamw', mesh, zero, net=net,
                         param_specs={net[0].weight.name: P('tp', None)})

    net_z, step_z, loss_z = run(True)
    net_r, step_r, loss_r = run(False)
    for a, b in zip(loss_z, loss_r):
        assert abs(a - b) <= 1e-6, (loss_z, loss_r)
    wname = net_z[0].weight.name
    zspec = step_z.zero_specs[wname]
    assert 'tp' in str(zspec) and 'dp' in str(zspec), zspec
    # physically laid out over both axes
    m = step_z._opt_state[wname][0]
    assert not m.sharding.is_fully_replicated


def test_compose_zero_spec_rules():
    assert compose_zero_spec((32, 16), P('tp', None), 'dp', 4) == \
        P('tp', 'dp')
    # already dp-sharded (fsdp-style specs): never compose a duplicate
    # axis — the state inherits the param's own 1/dp layout instead
    assert compose_zero_spec((32, 16), P('dp', None), 'dp', 4) is None
    assert compose_zero_spec((32, 16), P(('tp', 'dp'), None), 'dp', 4) \
        is None
    assert compose_zero_spec((32, 16), P(None, 'tp'), 'dp', 4) == \
        P('dp', 'tp')
    assert compose_zero_spec((32,), P(), 'dp', 8) == P('dp')
    # too small to shard -> stays replicated (the ragged/padding slack)
    assert compose_zero_spec((3,), P(), 'dp', 8) is None
    # uneven-but-large dims no longer shard raggedly: this jax refuses
    # uneven NamedShardings, so they stay replicated here (ZeRO-3
    # recovers them via flatten+pad — see zero3_layout) ...
    assert compose_zero_spec((12,), P(), 'dp', 8) is None
    # ... and a spec that itself PROPOSES dp on a non-divisible dim is
    # rejected up front with a clear error instead of deferring to an
    # opaque XLA refusal at device_put time
    with pytest.raises(MXNetError, match='not divisible'):
        compose_zero_spec((12, 16), P('dp', None), 'dp', 8)
    assert compose_zero_spec((), P(), 'dp', 8) is None


def test_zero1_with_fsdp_style_dp_sharded_param():
    """A param ALREADY sharded over dp by param_specs must not crash the
    build with a duplicate-axis spec: its state simply inherits the
    param's own 1/dp layout, and training still matches zero-off."""
    mesh = make_mesh((8,), ('dp',))

    def run(zero):
        net = _net()
        return _run_step('adamw', mesh, zero, net=net,
                         param_specs={net[0].weight.name: P('dp', None)})

    net_z, step_z, loss_z = run(True)
    _, _, loss_r = run(False)
    for a, b in zip(loss_z, loss_r):
        assert abs(a - b) <= 1e-6, (loss_z, loss_r)
    wname = net_z[0].weight.name
    assert step_z.zero_specs[wname] is None   # no duplicate composition
    # the moments are still 1/dp-sharded — via the param's own spec
    m = step_z._opt_state[wname][0]
    assert not m.sharding.is_fully_replicated


def test_zero1_flag_gate(monkeypatch):
    """MXTPU_ZERO=0 forces the replicated update; the explicit zero=
    argument wins over the env; dp=1 meshes never enable ZeRO."""
    mesh = make_mesh((8,), ('dp',))
    monkeypatch.setenv('MXTPU_ZERO', '0')
    step = ShardedTrainStep(_net(), gluon.loss.SoftmaxCrossEntropyLoss(),
                            'adamw', mesh=mesh)
    assert not step.zero
    step = ShardedTrainStep(_net(), gluon.loss.SoftmaxCrossEntropyLoss(),
                            'adamw', mesh=mesh, zero=True)
    assert step.zero
    monkeypatch.delenv('MXTPU_ZERO')
    step = ShardedTrainStep(_net(), gluon.loss.SoftmaxCrossEntropyLoss(),
                            'adamw', mesh=mesh)
    assert step.zero   # default-on with a >1-device dp axis
    step = ShardedTrainStep(_net(), gluon.loss.SoftmaxCrossEntropyLoss(),
                            'adamw', mesh=make_mesh((1, 8), ('dp', 'tp')))
    assert not step.zero


# ---------------------------------------------------------------------------
# comm telemetry contract
# ---------------------------------------------------------------------------

def test_zero1_comm_telemetry_accounting():
    """ZeRO swaps the grad all-reduce for reduce-scatter + all-gather at
    UNCHANGED total wire bytes (ring accounting), and the per-device
    optimizer-state gauge shows the 1/dp footprint."""
    mesh = make_mesh((8,), ('dp',))
    was_on = telemetry.enabled()
    telemetry.enable()
    try:
        telemetry.reset()
        _, step_z, _ = _run_step('adamw', mesh, zero=True, steps=2)
        rs = telemetry.value('mxnet_tpu_comm_collective_bytes_total',
                             kind='reduce_scatter', axis='dp',
                             stage='zero1')
        ag = telemetry.value('mxnet_tpu_comm_collective_bytes_total',
                             kind='all_gather', axis='dp', stage='zero1')
        n_rs = telemetry.value('mxnet_tpu_comm_collectives_total',
                               kind='reduce_scatter', axis='dp',
                               stage='zero1')
        gauge_z = telemetry.value(
            'mxnet_tpu_comm_opt_state_bytes_per_device')
        assert rs and ag and rs == ag
        assert n_rs == 2 * len(step_z._t_names)   # 2 steps, one per param
        assert gauge_z == step_z.opt_state_bytes_per_device()

        telemetry.reset()
        _, step_r, _ = _run_step('adamw', mesh, zero=False, steps=2)
        ar = telemetry.value('mxnet_tpu_comm_collective_bytes_total',
                             kind='all_reduce', axis='dp', stage='off')
        gauge_r = telemetry.value(
            'mxnet_tpu_comm_opt_state_bytes_per_device')
        assert telemetry.value('mxnet_tpu_comm_collective_bytes_total',
                               kind='reduce_scatter', axis='dp',
                               stage='off') is None
        assert ar == rs + ag   # same total traffic, different decomposition
        assert gauge_r >= 4 * gauge_z   # ~8x minus replicated scalars
    finally:
        if not was_on:
            telemetry.disable()


# ---------------------------------------------------------------------------
# layout-independent checkpoints: save at dp=8 -> restore at dp=4 / no-ZeRO
# ---------------------------------------------------------------------------

def test_zero1_checkpoint_dp8_to_dp4_bit_parity(tmp_path):
    """Acceptance: a checkpoint written under ZeRO at dp=8 restores
    bit-identical through CheckpointManager into a dp=4 ZeRO step AND
    into a non-ZeRO (replicated) step — the states payload is gathered
    host fp32, never the sharded layout."""
    from mxnet_tpu.checkpoint import CheckpointManager
    net = _net()
    loss_fn = gluon.loss.SoftmaxCrossEntropyLoss()
    x, y = _data()
    step8 = ShardedTrainStep(net, loss_fn, 'adamw',
                             {'learning_rate': 0.01},
                             mesh=make_mesh((8,), ('dp',)), zero=True)
    for _ in range(3):
        step8(x, y)
    mgr = CheckpointManager(str(tmp_path), params=net, trainer=step8,
                            async_save=False)
    mgr.save(3)
    mgr.close()
    saved = pickle.loads(step8.get_states_bytes())
    assert saved['zero'] and saved['dp'] == 8

    # manifest records the layout the checkpoint was written under
    from mxnet_tpu.checkpoint import manifest as mf
    doc = mf.read_manifest(mgr.step_dir(3))
    layout = doc['metadata']['optimizer_state_layout']
    assert layout == {'format': 'gathered-host', 'zero1': True,
                      'stage': 1, 'dp': 8}

    # reference trajectory: one MORE step on the saving instance (before
    # any restore mutates the shared net's params)
    step8(x, y)
    ref = pickle.loads(step8.get_states_bytes())
    ref_params = {n: p.data().asnumpy().copy()
                  for n, p in net.collect_params().items()}

    for target_mesh, target_zero in ((make_mesh((4,), ('dp',)), True),
                                     (make_mesh((8,), ('dp',)), False)):
        step_t = ShardedTrainStep(net, loss_fn, 'adamw',
                                  {'learning_rate': 0.01},
                                  mesh=target_mesh, zero=target_zero)
        mgr_t = CheckpointManager(str(tmp_path), params=net,
                                  trainer=step_t, async_save=False)
        assert mgr_t.restore_latest() == 3   # params + states -> step 3
        # the pending restored states apply lazily at the first build;
        # after one step the target must sit exactly where the saving
        # trainer sat after ITS fourth step
        step_t(x, y)
        got = pickle.loads(step_t.get_states_bytes())
        for n in ref['opt_state']:
            for a, b in zip(ref['opt_state'][n], got['opt_state'][n]):
                assert onp.allclose(onp.asarray(a), onp.asarray(b),
                                    rtol=0, atol=1e-6), (target_zero, n)
        for n, p in net.collect_params().items():
            d = float(onp.max(onp.abs(p.data().asnumpy() - ref_params[n])))
            assert d <= 1e-6, (target_zero, n, d)
        mgr_t.close()


def test_zero1_states_roundtrip_bit_identical():
    """get_states_bytes/set_states_bytes without the extra step: the
    gathered payload survives a zero(dp=8) -> replicated(dp=4) move
    bit-for-bit."""
    net = _net()
    loss_fn = gluon.loss.SoftmaxCrossEntropyLoss()
    x, y = _data()
    step8 = ShardedTrainStep(net, loss_fn, 'adamw',
                             {'learning_rate': 0.01},
                             mesh=make_mesh((8,), ('dp',)), zero=True)
    for _ in range(2):
        step8(x, y)
    blob = step8.get_states_bytes()
    step4 = ShardedTrainStep(net, loss_fn, 'adamw',
                             {'learning_rate': 0.01},
                             mesh=make_mesh((4,), ('dp',)), zero=False)
    step4(x, y)              # build (state now exists, will be overwritten)
    step4.set_states_bytes(blob)
    a = pickle.loads(blob)
    b = pickle.loads(step4.get_states_bytes())
    for n in a['opt_state']:
        for sa, sb in zip(a['opt_state'][n], b['opt_state'][n]):
            assert onp.array_equal(onp.asarray(sa), onp.asarray(sb)), n
    with pytest.raises(MXNetError, match='not a ShardedTrainStep'):
        step4.set_states_bytes(pickle.dumps({'format': 'bogus'}))
    # restore -> save BEFORE the first step (preemption window): the
    # pending payload is handed back unchanged instead of raising
    fresh = ShardedTrainStep(net, loss_fn, 'adamw',
                             {'learning_rate': 0.01},
                             mesh=make_mesh((4,), ('dp',)))
    with pytest.raises(MXNetError, match='no optimizer state yet'):
        fresh.get_states_bytes()
    fresh.set_states_bytes(blob)
    got = pickle.loads(fresh.get_states_bytes())
    for n in a['opt_state']:
        for sa, sb in zip(a['opt_state'][n], got['opt_state'][n]):
            assert onp.array_equal(onp.asarray(sa), onp.asarray(sb)), n


# ---------------------------------------------------------------------------
# gluon.Trainer path: the traced fused update learns the sharded layout
# ---------------------------------------------------------------------------

def _put_mesh(arr, mesh):
    """Commit an NDArray to the mesh (replicated): eager ops reject a
    batch committed to one device against mesh-committed weights."""
    arr._data = jax.device_put(arr._data, NamedSharding(mesh, P()))
    return arr


def _mesh_trainer(mesh, steps, optimizer='adam'):
    net = _net()
    x, y = _data()
    net(x)
    if mesh is not None:
        repl = NamedSharding(mesh, P())
        for p in net.collect_params().values():
            p.data()._data = jax.device_put(p.data()._data, repl)
        _put_mesh(x, mesh)
        _put_mesh(y, mesh)
    loss_fn = gluon.loss.SoftmaxCrossEntropyLoss()
    trainer = gluon.Trainer(net.collect_params(), optimizer,
                            {'learning_rate': 0.01})
    for _ in range(steps):
        with autograd.record():
            loss = loss_fn(net(x), y)
        loss.backward()
        trainer.step(x.shape[0])
    return net, trainer


def test_trainer_zero1_parity_and_sharded_states():
    """Trainer over mesh-replicated params activates ZeRO in the fused
    multi-tensor update (default-on), shards the Adam moments 1/dp, and
    trains bit-for-bit like the single-device trainer."""
    mesh = make_mesh((8,), ('dp',))
    net_z, tr_z = _mesh_trainer(mesh, steps=3)
    net_r, tr_r = _mesh_trainer(None, steps=3)
    assert tr_z._zero_active and tr_z._zero_dp == 8
    assert not tr_r._zero_active
    for (n, pz), (_, pr) in zip(sorted(net_z.collect_params().items()),
                                sorted(net_r.collect_params().items())):
        d = float(onp.max(onp.abs(pz.data().asnumpy()
                                  - pr.data().asnumpy())))
        assert d <= 1e-6, (n, d)
    # moments physically sharded
    some_sharded = False
    for st in tr_z._updater.states.values():
        for s in (st if isinstance(st, (list, tuple)) else [st]):
            if s is not None and s.ndim and hasattr(s._data, 'sharding'):
                some_sharded |= not s._data.sharding.is_fully_replicated
    assert some_sharded
    assert tr_z.opt_state_bytes_per_device() * 4 < \
        tr_r.opt_state_bytes_per_device()


def test_trainer_zero1_restore_into_non_zero_trainer():
    """Acceptance: states saved under ZeRO restore bit-identical into a
    non-ZeRO trainer (gathered-host payload), and the restored trainer
    re-scatters on its next fused step without diverging."""
    mesh = make_mesh((8,), ('dp',))
    net_z, tr_z = _mesh_trainer(mesh, steps=3)
    blob = tr_z.get_states_bytes()

    net_p, tr_p = _mesh_trainer(None, steps=3)   # plain, same trajectory
    tr_p.set_states_bytes(blob)
    a, b = pickle.loads(blob), pickle.loads(tr_p.get_states_bytes())

    def _leaves(s, out):
        if isinstance(s, (list, tuple)):
            for x in s:
                _leaves(x, out)
        elif s is not None:
            out.append(s)
        return out

    sa = a[0] if isinstance(a, tuple) else a
    sb = b[0] if isinstance(b, tuple) else b
    assert set(sa) == set(sb)
    for k in sa:
        for la, lb in zip(_leaves(sa[k], []), _leaves(sb[k], [])):
            assert onp.array_equal(onp.asarray(la), onp.asarray(lb)), k
    # and the zero trainer accepts its own payload back (re-scatter path)
    tr_z.set_states_bytes(blob)
    x, y = _data()
    _put_mesh(x, mesh)
    _put_mesh(y, mesh)
    loss_fn = gluon.loss.SoftmaxCrossEntropyLoss()
    with autograd.record():
        loss = loss_fn(net_z(x), y)
    loss.backward()
    tr_z.step(x.shape[0])
    assert tr_z._zero_active


def test_trainer_zero1_flag_gate(monkeypatch):
    monkeypatch.setenv('MXTPU_ZERO', '0')
    mesh = make_mesh((8,), ('dp',))
    _, tr = _mesh_trainer(mesh, steps=2)
    assert not tr._zero_active
    # zero OFF with mesh weights still places the states on the mesh
    # (replicated) — a jit cannot mix committed device sets
    for st in tr._updater.states.values():
        for s in (st if isinstance(st, (list, tuple)) else [st]):
            if s is not None and s.ndim:
                sh = s._data.sharding
                assert sh.is_fully_replicated
                assert getattr(sh, 'mesh', None) is not None \
                    and sh.mesh.size == 8


def test_trainer_multi_ctx_broadcast_batched():
    """Satellite: the post-update broadcast to the other context copies
    is ONE batched multi-array device_put per step (counted once under
    the comm contract), and still leaves every copy identical."""
    was_on = telemetry.enabled()
    telemetry.enable()
    try:
        telemetry.reset()
        net = nn.Dense(4, in_units=8)
        net.initialize(mx.init.Xavier(), ctx=[mx.cpu(0), mx.cpu(1)])
        tr = gluon.Trainer(net.collect_params(), 'sgd',
                           {'learning_rate': 0.1})
        rng = onp.random.RandomState(0)
        for _ in range(2):
            with autograd.record():
                l0 = net(nd.array(rng.randn(8, 8).astype(onp.float32),
                                  ctx=mx.cpu(0))).sum()
                l1 = net(nd.array(rng.randn(8, 8).astype(onp.float32),
                                  ctx=mx.cpu(1))).sum()
            autograd.backward([l0, l1])
            tr.step(16)
        for p in net.collect_params().values():
            d0, d1 = [d.asnumpy() for d in p.list_data()]
            assert onp.array_equal(d0, d1), p.name
        # one broadcast per step, bytes = (weight + bias) x extra copies
        assert telemetry.value('mxnet_tpu_comm_collectives_total',
                               kind='broadcast', axis='ctx') == 2
        assert telemetry.value('mxnet_tpu_comm_collective_bytes_total',
                               kind='broadcast', axis='ctx') == \
            2 * ((4 * 8 + 4) * 4)
    finally:
        if not was_on:
            telemetry.disable()


# ---------------------------------------------------------------------------
# gradient compression on the GSPMD path: routed for real (ISSUE 12) —
# the former rejection sites now apply the error-feedback codecs; only
# a genuinely unsupported ctype string still raises
# ---------------------------------------------------------------------------

def test_gradient_compression_routed_on_gspmd_paths():
    net = _net()
    loss_fn = gluon.loss.SoftmaxCrossEntropyLoss()
    # ShardedTrainStep: accepted and active (the error-feedback
    # epilogue runs inside the compiled step — see test_compression.py
    # for the trajectory/wire assertions)
    step = ShardedTrainStep(net, loss_fn, 'adamw',
                            mesh=make_mesh((8,), ('dp',)),
                            compression_params={'type': '2bit'})
    assert step.compression['type'] == '2bit'
    # type='none' is accepted (explicitly no compression)
    step = ShardedTrainStep(net, loss_fn, 'adamw',
                            mesh=make_mesh((8,), ('dp',)),
                            compression_params={'type': 'none'})
    assert step.compression is None
    # unknown ctype: actionable error at construction
    with pytest.raises(MXNetError, match='not supported'):
        ShardedTrainStep(net, loss_fn, 'adamw',
                         mesh=make_mesh((8,), ('dp',)),
                         compression_params={'type': '3bit'})
    # Trainer single-copy path: the push that would compress is
    # skipped, so the codec applies to the single gradient copy in
    # place — the step RUNS and the gradient is quantized
    x, y = _data()
    net(x)
    trainer = gluon.Trainer(net.collect_params(), 'sgd',
                            {'learning_rate': 0.1},
                            compression_params={'type': '2bit',
                                                'threshold': 0.05})
    with autograd.record():
        loss = loss_fn(net(x), y)
    loss.backward()
    trainer.step(x.shape[0])
    g = next(iter(net.collect_params().values())).list_grad()[0].asnumpy()
    lvls = onp.array([-0.05, 0.0, 0.05], onp.float32)
    assert onp.all(onp.min(onp.abs(g[..., None] - lvls), axis=-1) < 1e-7), \
        "single-copy gradient was not 2bit-quantized in place"
    # Trainer without a kvstore: the trainer-local compressor applies
    # to the merged gradient in _update
    net2 = _net()
    net2(x)
    trainer = gluon.Trainer(net2.collect_params(), 'sgd',
                            {'learning_rate': 0.1}, kvstore=None,
                            compression_params={'type': '2bit'})
    with autograd.record():
        loss = loss_fn(net2(x), y)
    loss.backward()
    trainer.step(x.shape[0])
    assert trainer._local_gc is not None and trainer._local_gc._residual
    # unsupported ctype gets an actionable error, not an AssertionError
    from mxnet_tpu.kvstore.gradient_compression import GradientCompression
    with pytest.raises(MXNetError, match="'1bit'"):
        GradientCompression('1bit')
    # fp16/int8 are REAL codecs on the kvstore path now
    for ctype in ('fp16', 'int8'):
        gc = GradientCompression(ctype)
        out = gc.compress_decompress(nd.array([0.30000001, -1.5]), 'k')
        assert onp.all(onp.isfinite(out.asnumpy()))
