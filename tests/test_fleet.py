"""Fleet observability plane (ISSUE 13): /metrics + /healthz + /flight
endpoints, heartbeat-piggybacked telemetry snapshots, the coordinator's
fleet view + anomaly detectors, clock-offset estimation and distributed
trace stitching.

The two-process drill at the bottom is the acceptance path: two real
ranks with endpoints armed, an injected slow rank flagged by the
straggler detector and named in the watchdog verdict, fleet gauges
agreeing exactly with the per-rank comm counters, and the stitched
trace passing tools/check_trace.py.
"""
import json
import os
import socket
import subprocess
import sys
import threading
import time
import tracemalloc
import urllib.error
import urllib.request

import pytest

import mxnet_tpu as mx
from mxnet_tpu import checkpoint, telemetry
from mxnet_tpu.parallel import dist
from mxnet_tpu.resilience import StepWatchdog
from mxnet_tpu.resilience.elastic import stall_verdict
from mxnet_tpu.telemetry import fleet, flight, server, trace

TOOLS = os.path.join(os.path.dirname(__file__), os.pardir, 'tools')


@pytest.fixture(autouse=True)
def _clean_state():
    telemetry.disable()
    telemetry.reset()
    trace.disable()
    trace.clear()
    flight.get().clear()
    fleet._monitor = None
    server.stop()
    yield
    telemetry.disable()
    telemetry.reset()
    trace.disable()
    trace.clear()
    flight.get().clear()
    fleet._monitor = None
    server.stop()


def _free_port():
    with socket.socket() as s:
        s.bind(('', 0))
        return s.getsockname()[1]


def _wait_until(cond, timeout=5.0):
    """Snapshot hooks run AFTER the beat reply is written (so the
    detector pass can't inflate the sender's measured RTT) — a worker's
    beat() returning does not mean the coordinator has ingested yet."""
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        if cond():
            return True
        time.sleep(0.01)
    return cond()


def _get(url, timeout=5.0):
    try:
        with urllib.request.urlopen(url, timeout=timeout) as r:
            return r.status, r.read().decode()
    except urllib.error.HTTPError as e:
        return e.code, e.read().decode()


# ---------------------------------------------------------------------------
# clock-offset estimation
# ---------------------------------------------------------------------------

def test_estimate_offset_prefers_min_rtt():
    # sample 1: rtt 100ms, midpoint 0.05, remote said 5.05 -> off 5.0
    # sample 2: rtt 20ms, midpoint 1.01, remote said 6.013 -> off 5.003
    off, rtt = fleet.estimate_offset(
        [(0.0, 0.10, 5.05), (1.0, 1.02, 6.013)])
    assert abs(off - 5.003) < 1e-9
    assert abs(rtt - 0.02) < 1e-9
    assert fleet.estimate_offset([]) is None


def test_estimate_offset_monotonic_rtt_beats_wallclock_step():
    # an NTP step backward between send and receive fabricates a
    # near-zero WALL-clock rtt; the explicit monotonic rtt (4th
    # element) must be what the min-RTT selection ranks by
    honest = (10.0, 10.002, 15.001, 0.002)        # off 5.0, rtt 2 ms
    poisoned = (20.0, 19.951, 24.9755, 0.049)     # clock stepped -50ms
    off, rtt = fleet.estimate_offset([poisoned, honest])
    assert abs(off - 5.0) < 1e-9 and rtt == 0.002
    # 3-tuple fallback still works for offline wall-clock recordings
    assert fleet.estimate_offset([(0.0, 0.1, 5.05)]) is not None


def test_attach_plumbs_real_heartbeat_into_stale_threshold():
    port = _free_port()
    ms0 = dist.Membership(0, 2, port=port, heartbeat_seconds=10.0,
                          deadline_seconds=60.0, start=False)
    try:
        mon = fleet.attach(ms0)
        # env knob default is 1.0s -> auto threshold would be 3.0s and
        # flag every healthy rank stale between 10s beats
        assert mon.stale_seconds == 30.0, mon.stale_seconds
        explicit = fleet.FleetMonitor(stale_seconds=7.0)
        explicit.set_heartbeat(10.0)
        assert explicit.stale_seconds == 7.0      # explicit wins
    finally:
        fleet.detach(ms0)
        ms0.stop()


def test_membership_clock_offset_roundtrip():
    port = _free_port()
    ms0 = dist.Membership(0, 2, port=port, heartbeat_seconds=0.1,
                          deadline_seconds=30.0, start=False)
    ms0.start()
    ms1 = dist.Membership(1, 2, port=port, heartbeat_seconds=0.1,
                          deadline_seconds=30.0, start=False)
    try:
        assert ms1.clock_offset() is None        # no round-trip yet
        for _ in range(3):
            ms1.beat()
        off, rtt = ms1.clock_offset()
        # same host, same clock: the offset must be tiny and the rtt
        # bounded by a loopback round-trip
        assert abs(off) < 0.5 and 0.0 <= rtt < 0.5
        assert ms0.clock_offset() == (0.0, 0.0)  # reference clock
    finally:
        ms0.stop()
        ms1.stop()


# ---------------------------------------------------------------------------
# local snapshots
# ---------------------------------------------------------------------------

def test_local_snapshot_disarmed_is_none():
    assert fleet.local_snapshot() is None
    assert fleet.snapshot_bytes() == 0


def test_local_snapshot_carries_step_spans_comm_counters():
    telemetry.enable()
    trace.enable()
    with trace.span('step.dispatch'):
        with trace.span('io.batch'):
            pass
    flight.get().record_step(1)
    time.sleep(0.005)
    with trace.span('h2d.device_put'):
        pass
    flight.get().record_step(2)
    telemetry.counter('mxnet_tpu_comm_collective_bytes_total').inc(
        1000, kind='all_reduce', axis='dp', stage='zero1')
    telemetry.counter('mxnet_tpu_comm_collective_bytes_total').inc(
        24, kind='all_gather', axis='dph', stage='zero1')
    telemetry.inc('mxnet_tpu_resilience_faults_injected_total',
                  site='io.decode', fault_kind='raise')
    snap = fleet.local_snapshot()
    assert snap['step'] == 2
    assert snap['wall_ms'] > 0
    assert 'h2d' in snap['spans_ms']
    assert snap['comm_bytes'] == {'dp': 1000, 'dph': 24}
    assert snap['counters'] == {'faults': 1}
    n = fleet.snapshot_bytes(snap)
    assert 0 < n < 1024, f"snapshot unexpectedly large: {n} bytes"


def test_snapshot_bytes_includes_the_offset_field():
    telemetry.enable()
    trace.enable()
    with trace.span('step.dispatch'):
        pass
    flight.get().record_step(1)

    class _MS:
        def clock_offset(self):
            return (0.000123, 0.0009)
    bare = fleet.snapshot_bytes(fleet.local_snapshot())
    wired = fleet.snapshot_bytes(membership=_MS())
    # the measured number must be what the heartbeat actually carries —
    # the provider-appended offset field included
    assert wired > bare, (wired, bare)


def test_comm_bytes_by_axis_aggregates_kinds():
    telemetry.enable()
    c = telemetry.counter('mxnet_tpu_comm_collective_bytes_total')
    c.inc(10, kind='all_gather', axis='dp', stage='zero1')
    c.inc(5, kind='reduce_scatter', axis='dp', stage='zero1')
    c.inc(7, kind='all_reduce', axis='dph', stage='off')
    assert fleet.comm_bytes_by_axis() == {'dp': 15, 'dph': 7}


# ---------------------------------------------------------------------------
# fleet view merge + detectors
# ---------------------------------------------------------------------------

def _mon(**kw):
    kw.setdefault('heartbeat_seconds', 0.1)
    kw.setdefault('stale_seconds', 30.0)
    return fleet.FleetMonitor(**kw)


def test_fleet_view_contains_ranks_and_skew():
    mon = _mon()
    for step in range(1, 4):
        mon.ingest(0, {'step': step, 'wall_ms': 100.0, 'loss': 1.0})
        mon.ingest(1, {'step': step, 'wall_ms': 300.0, 'loss': 1.1})
    v = mon.view()
    assert sorted(v['ranks']) == [0, 1]
    assert v['fleet']['ranks'] == 2
    assert v['fleet']['max_step'] == 3
    # skew is against the fleet median (200): symmetric here
    assert v['ranks'][0]['skew_ms'] == -100.0
    assert v['ranks'][1]['skew_ms'] == 100.0
    assert v['ranks'][1]['wall_ms'] == 300.0


def test_straggler_detector_flags_slow_rank():
    mon = _mon(straggler_factor=1.5)
    fired = []
    for step in range(1, 6):
        fired += mon.ingest(0, {'step': step, 'wall_ms': 100.0})
        fired += mon.ingest(2, {'step': step, 'wall_ms': 105.0})
        fired += mon.ingest(1, {'step': step, 'wall_ms': 400.0})
    kinds = [(k, i['rank']) for k, i in fired]
    assert ('fleet.straggler', 1) in kinds
    s = mon.straggler()
    assert s['rank'] == 1 and s['reason'] == 'slow' and s['flagged']
    assert s['wall_ms'] == 400.0


def test_straggler_detector_flags_stale_rank():
    mon = _mon(stale_seconds=0.05)
    mon.ingest(1, {'step': 1, 'wall_ms': 100.0})
    time.sleep(0.12)
    fired = mon.ingest(0, {'step': 1, 'wall_ms': 100.0})
    stale = [i for k, i in fired if k == 'fleet.straggler'
             and i['reason'] == 'stale']
    assert stale and stale[0]['rank'] == 1
    assert stale[0]['snapshot_age_seconds'] >= 0.05
    s = mon.straggler()
    assert s['rank'] == 1 and s['reason'] == 'stale'
    # a fresh snapshot clears the flag
    mon.ingest(1, {'step': 2, 'wall_ms': 100.0})
    assert mon.straggler() is None


def test_step_time_regression_detector():
    mon = _mon(regression_factor=2.0)
    fired = []
    for step in range(1, 6):
        fired += mon.ingest(0, {'step': step, 'wall_ms': 100.0})
    assert not fired
    fired = mon.ingest(0, {'step': 6, 'wall_ms': 500.0})
    kinds = [k for k, _i in fired]
    assert 'fleet.step_regression' in kinds
    info = dict(fired)['fleet.step_regression']
    assert info['rank'] == 0 and info['factor'] >= 2.0
    # latched: no duplicate note while the excursion continues
    again = mon.ingest(0, {'step': 7, 'wall_ms': 500.0})
    assert 'fleet.step_regression' not in [k for k, _ in again]


def test_regression_detector_uses_pre_update_baseline():
    # the excursion must be judged against the baseline as it stood
    # BEFORE the sample — folding it in first made factor >= 5
    # mathematically unfirable (review finding)
    mon = _mon(regression_factor=5.0)
    for step in range(1, 6):
        mon.ingest(0, {'step': step, 'wall_ms': 100.0})
    fired = mon.ingest(0, {'step': 6, 'wall_ms': 600.0})
    kinds = [k for k, _ in fired]
    assert 'fleet.step_regression' in kinds, fired
    info = dict(fired)['fleet.step_regression']
    assert info['baseline_ms'] == 100.0 and info['factor'] == 6.0


def test_comm_imbalance_flag_clears_when_offender_changes():
    mon = _mon(imbalance_factor=1.5)
    for step in range(1, 4):
        mon.ingest(0, {'step': step, 'wall_ms': 100.0,
                       'comm_bytes': {'dp': 1000 * step}})
        mon.ingest(1, {'step': step, 'wall_ms': 100.0,
                       'comm_bytes': {'dp': 5000 * step}})
    assert 'fleet.comm_imbalance' in mon.ranks[1].flags
    # traffic shifts: rank 0 becomes the heavy one — rank 1's flag
    # must clear (a stuck flag would latch-swallow its next offense)
    fired = []
    for step in range(4, 8):
        fired += mon.ingest(0, {'step': step, 'wall_ms': 100.0,
                                'comm_bytes': {'dp': 3000 + 50000 * step}})
        fired += mon.ingest(1, {'step': step, 'wall_ms': 100.0,
                                'comm_bytes': {'dp': 15000 + 1000 * step}})
    assert 'fleet.comm_imbalance' not in mon.ranks[1].flags
    hits = [i for k, i in fired if k == 'fleet.comm_imbalance']
    assert hits and hits[-1]['rank'] == 0


def test_refresh_after_removal_does_not_resurrect_rows():
    telemetry.enable()
    mon = _mon()
    fleet._monitor = mon
    mon.ingest(0, {'step': 1, 'wall_ms': 100.0})
    mon.ingest(1, {'step': 1, 'wall_ms': 100.0})
    mon.remove_ranks([1])
    mon.refresh_gauges()
    assert telemetry.value('mxnet_tpu_fleet_snapshot_age_seconds',
                           rank=1) is None
    assert telemetry.value('mxnet_tpu_fleet_ranks') == 1


def test_loss_spike_detector():
    mon = _mon(loss_spike_sigma=6.0)
    fired = []
    for step in range(1, 13):
        fired += mon.ingest(0, {'step': step, 'wall_ms': 100.0,
                                'loss': 1.0 + 0.01 * (step % 3)})
    assert not [k for k, _ in fired if k == 'fleet.loss_spike']
    fired = mon.ingest(0, {'step': 13, 'wall_ms': 100.0, 'loss': 50.0})
    assert [k for k, _ in fired] == ['fleet.loss_spike']
    info = dict(fired)['fleet.loss_spike']
    assert info['rank'] == 0 and info['sigma'] >= 6.0


def test_loss_spike_fires_from_flat_baseline():
    # std == 0 (identical losses) is where a jump is MOST anomalous —
    # the zero-std guard must not make the detector unfirable
    mon = _mon(loss_spike_sigma=6.0)
    for step in range(1, 11):
        mon.ingest(0, {'step': step, 'wall_ms': 100.0, 'loss': 1.0})
    fired = mon.ingest(0, {'step': 11, 'wall_ms': 100.0, 'loss': 100.0})
    assert [k for k, _ in fired] == ['fleet.loss_spike'], fired


def test_comm_imbalance_detector():
    mon = _mon(imbalance_factor=1.5)
    fired = []
    for step in range(1, 4):
        fired += mon.ingest(0, {'step': step, 'wall_ms': 100.0,
                                'comm_bytes': {'dp': 1000 * step}})
        fired += mon.ingest(1, {'step': step, 'wall_ms': 100.0,
                                'comm_bytes': {'dp': 5000 * step}})
    hits = [i for k, i in fired if k == 'fleet.comm_imbalance']
    assert hits and hits[0]['rank'] == 1 and hits[0]['ratio'] >= 4.9


def test_anomalies_emit_flight_notes_and_metrics():
    telemetry.enable()
    trace.enable()                    # flight notes require the tracer
    mon = _mon(straggler_factor=1.5)
    for step in range(1, 6):
        mon.ingest(0, {'step': step, 'wall_ms': 100.0})
        mon.ingest(1, {'step': step, 'wall_ms': 400.0})
    notes = [e for e in flight.get().events()
             if e['kind'] == 'fleet.straggler']
    assert notes and notes[0]['rank'] == 1
    assert telemetry.value('mxnet_tpu_fleet_anomalies_total',
                           kind='fleet.straggler', rank=1) >= 1
    assert telemetry.value('mxnet_tpu_fleet_ranks') == 2
    assert telemetry.value('mxnet_tpu_fleet_step_ms', rank=1) == 400.0


def test_fleet_comm_gauge_mirrors_rank_totals():
    telemetry.enable()
    mon = _mon()
    mon.ingest(1, {'step': 1, 'wall_ms': 10.0,
                   'comm_bytes': {'dp': 1234}})
    mon.ingest(1, {'step': 2, 'wall_ms': 10.0,
                   'comm_bytes': {'dp': 2468}})
    assert telemetry.value('mxnet_tpu_fleet_comm_bytes',
                           rank=1, axis='dp') == 2468
    v = mon.view()
    assert v['ranks'][1]['comm_bytes_total'] == {'dp': 2468}
    assert v['ranks'][1]['comm_bytes_per_step'] == {'dp': 1234}


# ---------------------------------------------------------------------------
# membership piggyback wiring
# ---------------------------------------------------------------------------

def test_attach_pipes_snapshots_to_coordinator_monitor():
    telemetry.enable()
    trace.enable()
    port = _free_port()
    ms0 = dist.Membership(0, 2, port=port, heartbeat_seconds=0.1,
                          deadline_seconds=30.0, start=False)
    ms0.start()
    ms1 = dist.Membership(1, 2, port=port, heartbeat_seconds=0.1,
                          deadline_seconds=30.0, start=False)
    try:
        mon = fleet.attach(ms0)
        assert fleet.attach(ms1) is None         # workers get no monitor
        with trace.span('step.dispatch'):
            pass
        flight.get().record_step(1)
        ms0.beat()
        ms1.beat()
        assert _wait_until(
            lambda: sorted(mon.view()['ranks']) == [0, 1]), mon.view()
        snaps = ms0.fleet_snapshots()
        assert set(snaps) == {0, 1}
        assert snaps[1]['snap']['step'] == 1
    finally:
        fleet.detach(ms0)
        fleet.detach(ms1)
        ms0.stop()
        ms1.stop()


def test_removed_rank_gauge_rows_are_retired():
    telemetry.enable()
    mon = _mon()
    mon.ingest(0, {'step': 1, 'wall_ms': 100.0, 'loss': 1.0})
    mon.ingest(1, {'step': 1, 'wall_ms': 300.0, 'loss': 1.2,
                   'comm_bytes': {'dp': 10}})
    assert telemetry.value('mxnet_tpu_fleet_step_ms', rank=1) == 300.0
    mon.remove_ranks([1])
    # every per-rank series of the departed rank is gone from scrapes
    # (a frozen ghost row would read as "perfectly fresh" forever)
    for name in ('mxnet_tpu_fleet_step_ms', 'mxnet_tpu_fleet_last_step',
                 'mxnet_tpu_fleet_loss',
                 'mxnet_tpu_fleet_snapshot_age_seconds'):
        assert telemetry.value(name, rank=1) is None, name
    assert not [lb for lb, _v in
                telemetry.series('mxnet_tpu_fleet_comm_bytes')
                if lb.get('rank') == '1']
    assert telemetry.value('mxnet_tpu_fleet_step_ms', rank=0) == 100.0
    assert telemetry.value('mxnet_tpu_fleet_ranks') == 1


def test_worker_stall_verdict_reads_reply_straggler():
    telemetry.enable()
    trace.enable()
    port = _free_port()
    ms0 = dist.Membership(0, 2, port=port, heartbeat_seconds=0.1,
                          deadline_seconds=30.0, start=False)
    ms0.start()
    ms1 = dist.Membership(1, 2, port=port, heartbeat_seconds=0.1,
                          deadline_seconds=30.0, start=False)
    try:
        mon = fleet.attach(ms0)
        fleet.attach(ms1)
        # flag rank 1 as the slow straggler on the coordinator
        for step in range(1, 6):
            mon.ingest(0, {'step': step, 'wall_ms': 100.0})
            mon.ingest(1, {'step': step, 'wall_ms': 400.0})
        assert mon.straggler()['rank'] == 1
        ms1.beat()                    # reply carries the summary
        assert (ms1.view() or {}).get('straggler', {}).get('rank') == 1
        # a WORKER's watchdog (no local monitor) must still name the
        # suspect — (world-1)/world of wedges happen off-coordinator
        fleet._monitor = None
        v = stall_verdict(ms1)
        assert v['verdict'] == 'straggler_suspected', v
        assert v['straggler']['rank'] == 1 and v['straggler']['flagged']
        report = StepWatchdog(deadline_seconds=999.0, membership=ms1
                              )._format_report(1.0, 5)
        assert 'STRAGGLER SUSPECTED: rank 1' in report
    finally:
        fleet.detach(ms0)
        fleet.detach(ms1)
        ms0.stop()
        ms1.stop()


def test_removed_rank_is_evicted_not_latched_stale():
    # a departed rank must not haunt the straggler verdict: without
    # eviction its snapshot age only grows and the 'stale' flag could
    # never clear (review finding on the PR-8 re-form path)
    mon = _mon(stale_seconds=0.05)
    mon.ingest(0, {'step': 1, 'wall_ms': 100.0})
    mon.ingest(1, {'step': 1, 'wall_ms': 100.0})
    time.sleep(0.12)
    mon.ingest(0, {'step': 2, 'wall_ms': 100.0})
    assert mon.straggler()['rank'] == 1          # latched stale
    mon.remove_ranks([1])
    assert mon.straggler() is None
    assert sorted(mon.view()['ranks']) == [0]


def test_remove_peers_evicts_rank_from_monitor():
    telemetry.enable()
    trace.enable()
    port = _free_port()
    ms0 = dist.Membership(0, 3, port=port, heartbeat_seconds=0.1,
                          deadline_seconds=30.0, start=False)
    ms0.start()
    ms1 = dist.Membership(1, 3, port=port, heartbeat_seconds=0.1,
                          deadline_seconds=30.0, start=False)
    try:
        mon = fleet.attach(ms0)
        fleet.attach(ms1)
        with trace.span('step.dispatch'):
            pass
        flight.get().record_step(1)
        ms0.beat()
        ms1.beat()
        assert _wait_until(
            lambda: sorted(mon.view()['ranks']) == [0, 1]), mon.view()
        # both the coordinator's own call and a worker's request route
        # through the on_peers_removed hook
        ms0.remove_peers([1])
        assert sorted(mon.view()['ranks']) == [0]
        assert 1 not in ms0.fleet_snapshots()
    finally:
        fleet.detach(ms0)
        fleet.detach(ms1)
        ms0.stop()
        ms1.stop()


def test_become_coordinator_reattaches_fleet():
    port0, port1 = _free_port(), _free_port()
    ms1 = dist.Membership(1, 2, port=port0, heartbeat_seconds=0.1,
                          deadline_seconds=30.0, start=False)
    try:
        assert fleet.attach(ms1) is None         # worker: provider only
        assert ms1.telemetry_provider is not None
        assert ms1.on_snapshot is None
        ms1.port = port1                         # promote on a free port
        ms1.become_coordinator()
        # promotion made this rank the merge point: monitor created,
        # snapshots ingested, removals mirrored
        assert ms1.on_snapshot is not None
        assert fleet.monitor() is not None
        assert ms1.on_peers_removed is not None
    finally:
        ms1.stop()


def test_export_writes_only_ingesting_ranks_gauges():
    telemetry.enable()
    mon = _mon()
    mon.ingest(0, {'step': 1, 'wall_ms': 100.0})
    mon.ingest(1, {'step': 1, 'wall_ms': 300.0})
    # rank 1's ingest must not rewrite rank 0's skew against the new
    # median — rank 0's row refreshes on ITS next beat (O(world) per
    # heartbeat period, not O(world^2))
    skew0 = telemetry.value('mxnet_tpu_fleet_step_skew_ms', rank=0)
    skew1 = telemetry.value('mxnet_tpu_fleet_step_skew_ms', rank=1)
    assert skew0 == 0.0          # written when rank 0 was alone
    assert skew1 == 100.0        # vs median(100, 300) = 200
    mon.ingest(0, {'step': 2, 'wall_ms': 100.0})
    assert telemetry.value('mxnet_tpu_fleet_step_skew_ms',
                           rank=0) == -100.0


# ---------------------------------------------------------------------------
# HTTP endpoints
# ---------------------------------------------------------------------------

def test_server_endpoints_and_404():
    telemetry.enable()
    trace.enable()
    telemetry.inc('mxnet_tpu_steps_total')
    with trace.span('step.dispatch'):
        pass
    flight.get().record_step(1)
    srv = server.TelemetryServer(port=0)
    base = f'http://127.0.0.1:{srv.port}'
    try:
        code, body = _get(base + '/metrics')
        assert code == 200 and 'mxnet_tpu_steps_total 1' in body
        code, body = _get(base + '/healthz')
        assert code == 200
        doc = json.loads(body)
        assert doc['status'] == 'ok' and doc['telemetry'] is True
        assert doc['last_step'] == 1
        code, body = _get(base + '/flight')
        assert code == 200
        doc = json.loads(body)
        assert doc['steps'][0]['step'] == 1
        assert 'traceEvents' in doc
        code, body = _get(base + '/nope')
        assert code == 404
    finally:
        srv.stop()


def test_server_bounded_handlers_shed_load():
    srv = server.TelemetryServer(port=0, max_handlers=2)
    base = f'http://127.0.0.1:{srv.port}'
    results = []

    def hit():
        try:
            results.append(_get(base + '/metrics', timeout=5)[0])
        except Exception as e:
            results.append(repr(e))
    try:
        threads = [threading.Thread(target=hit) for _ in range(16)]
        for t in threads:
            t.start()
        for t in threads:
            t.join(timeout=10)
        # the server survives the storm: some requests answered, the
        # rest shed (connection reset), and it still answers afterwards
        assert any(r == 200 for r in results), results
        assert _get(base + '/metrics')[0] == 200
    finally:
        srv.stop()


def test_trickling_client_cannot_hold_a_slot_past_deadline():
    # a client feeding one byte per interval resets the socket timeout
    # every recv — the per-request wall deadline must still cut it off
    # so it cannot starve the bounded handler pool (slow-loris)
    srv = server.TelemetryServer(port=0, max_handlers=2)
    try:
        s = socket.create_connection(('127.0.0.1', srv.port), timeout=5)
        t0 = time.monotonic()
        s.sendall(b'G')
        closed = False
        while time.monotonic() - t0 < 10.0:
            time.sleep(0.3)
            try:
                s.sendall(b'X')
            except OSError:
                closed = True
                break
        assert closed, "trickling connection survived the deadline"
        assert time.monotonic() - t0 < 9.0
        s.close()
        assert _get(f'http://127.0.0.1:{srv.port}/metrics')[0] == 200
    finally:
        srv.stop()


def test_healthz_reports_last_committed_step(tmp_path):
    import numpy as onp
    mgr = checkpoint.CheckpointManager(str(tmp_path), async_save=False,
                                       replication=False)
    mgr.save(7, params={'w': onp.zeros(4, onp.float32)}, block=True)
    srv = server.TelemetryServer(port=0)
    try:
        doc = json.loads(_get(f'http://127.0.0.1:{srv.port}/healthz')[1])
        assert doc['last_committed_step'] == 7
        assert checkpoint.last_committed_step() == 7
    finally:
        srv.stop()
        mgr.close()


def test_server_knob_gate(monkeypatch):
    monkeypatch.delenv('MXTPU_METRICS_PORT', raising=False)
    assert server.maybe_start(rank=0) is None
    port = _free_port()
    monkeypatch.setenv('MXTPU_METRICS_PORT', str(port))
    srv = server.maybe_start(rank=0)
    try:
        assert srv is not None and srv.port == port
        assert server.start(rank=0) is srv       # idempotent
    finally:
        server.stop()


def test_scrape_refreshes_silent_ranks_age_gauge():
    telemetry.enable()
    mon = _mon()
    fleet._monitor = mon
    mon.ingest(0, {'step': 1, 'wall_ms': 100.0})
    mon.ingest(1, {'step': 1, 'wall_ms': 100.0})
    # rank 1 goes SILENT: its age gauge froze at ~0 (stamped by its own
    # last ingest) — the /metrics scrape must re-export a GROWING age,
    # or an alert on it can never fire for the rank that matters
    time.sleep(0.15)
    mon.ingest(0, {'step': 2, 'wall_ms': 100.0})
    frozen = telemetry.value('mxnet_tpu_fleet_snapshot_age_seconds',
                             rank=1)
    assert frozen is not None and frozen < 0.1
    srv = server.TelemetryServer(port=0)
    try:
        body = _get(f'http://127.0.0.1:{srv.port}/metrics')[1]
    finally:
        srv.stop()
    age = telemetry.value('mxnet_tpu_fleet_snapshot_age_seconds', rank=1)
    assert age >= 0.15, age
    assert 'mxnet_tpu_fleet_snapshot_age_seconds{rank="1"}' in body


def test_thread_exhaustion_releases_handler_slot(monkeypatch):
    srv = server.TelemetryServer(port=0, max_handlers=2)
    base = f'http://127.0.0.1:{srv.port}'
    try:
        assert _get(base + '/metrics')[0] == 200

        class _Unstartable:
            def __init__(self, *a, **kw):
                pass

            def start(self):
                raise RuntimeError("can't start new thread")
        # every accept during the outage must give its pool slot BACK —
        # a leak would brick the endpoint after max_handlers failures
        monkeypatch.setattr(server.threading, 'Thread', _Unstartable)
        for _ in range(8):
            try:
                _get(base + '/metrics', timeout=2)
            except Exception:
                pass
        monkeypatch.undo()
        time.sleep(0.1)
        assert _get(base + '/metrics')[0] == 200
    finally:
        srv.stop()


# ---------------------------------------------------------------------------
# straggler verdict (watchdog upgrade)
# ---------------------------------------------------------------------------

class _FakeMembership:
    rank = 0
    deadline_seconds = 10.0

    def lost_peers(self):
        return []

    def peer_ages(self):
        return {1: 0.1}

    def clock_offset(self):
        return (0.0, 0.0)


def test_stall_verdict_upgrades_to_straggler_suspected():
    mon = _mon(straggler_factor=1.5)
    for step in range(1, 6):
        mon.ingest(0, {'step': step, 'wall_ms': 100.0})
        mon.ingest(1, {'step': step, 'wall_ms': 400.0})
    fleet._monitor = mon
    v = stall_verdict(_FakeMembership())
    assert v['verdict'] == 'straggler_suspected'
    assert v['straggler']['rank'] == 1 and v['straggler']['flagged']
    report = StepWatchdog(deadline_seconds=999.0,
                          membership=_FakeMembership()
                          )._format_report(1.0, 5)
    assert 'STRAGGLER SUSPECTED: rank 1' in report
    assert 'last snapshot' in report


def test_stall_verdict_local_stall_names_worst_rank_unflagged():
    mon = _mon(straggler_factor=10.0)     # threshold never trips
    for step in range(1, 6):
        mon.ingest(0, {'step': step, 'wall_ms': 100.0})
        mon.ingest(1, {'step': step, 'wall_ms': 130.0})
    fleet._monitor = mon
    v = stall_verdict(_FakeMembership())
    assert v['verdict'] == 'local_stall'
    s = v['straggler']
    assert s['rank'] == 1 and not s['flagged']   # worst-of-fleet hint
    report = StepWatchdog(deadline_seconds=999.0,
                          membership=_FakeMembership()
                          )._format_report(1.0, 5)
    assert 'LOCAL STALL' in report and 'worst rank: 1' in report


# ---------------------------------------------------------------------------
# disarmed cost: zero-alloc on the step path (the PR 6 discipline)
# ---------------------------------------------------------------------------

def test_disarmed_fleet_paths_allocate_nothing():
    assert not trace.enabled() and not telemetry.enabled()

    def hot_loop(n):
        for _ in range(n):
            with trace.span('step.dispatch'):
                pass
            flight.record_step(1)
            fleet.local_snapshot()
    hot_loop(64)                       # warm lazy interpreter state
    tracemalloc.start()
    before = tracemalloc.take_snapshot()
    hot_loop(2000)
    after = tracemalloc.take_snapshot()
    tracemalloc.stop()
    grown = sum(d.size_diff for d in after.compare_to(before, 'filename')
                if d.size_diff > 0)
    assert grown < 4096, f"disarmed fleet path leaked {grown} bytes"
    assert flight.get().steps() == []


# ---------------------------------------------------------------------------
# flight-dir routing (the CWD-litter fix)
# ---------------------------------------------------------------------------

def test_flight_default_path_not_cwd(monkeypatch, tmp_path):
    monkeypatch.delenv('MXTPU_FLIGHT_PATH', raising=False)
    monkeypatch.delenv('MXTPU_FLIGHT_DIR', raising=False)
    p = flight.default_dump_path()
    assert os.path.isabs(p)
    assert os.path.dirname(p) != os.getcwd()
    assert f'mxtpu_flight-{os.getpid()}.json' in p
    monkeypatch.setenv('MXTPU_FLIGHT_DIR', str(tmp_path))
    assert flight.default_dump_path().startswith(str(tmp_path))
    monkeypatch.setenv('MXTPU_FLIGHT_PATH', str(tmp_path / 'x.json'))
    assert flight.default_dump_path() == str(tmp_path / 'x.json')


def test_flight_dump_lands_in_flight_dir(monkeypatch, tmp_path):
    monkeypatch.delenv('MXTPU_FLIGHT_PATH', raising=False)
    monkeypatch.setenv('MXTPU_FLIGHT_DIR', str(tmp_path))
    trace.enable()
    with trace.span('step.dispatch'):
        pass
    flight.get().record_step(1)
    path = flight.dump(reason='test')
    assert path and path.startswith(str(tmp_path)), path
    assert json.load(open(path))['reason'] == 'test'


# ---------------------------------------------------------------------------
# trace stitching
# ---------------------------------------------------------------------------

def _rank_doc(rank, offset_us, t0=1_000_000.0, open_span=False):
    evs = [
        {'name': 'thread_name', 'ph': 'M', 'pid': 1, 'tid': 1,
         'args': {'name': 'main'}},
        {'name': 'step.dispatch', 'cat': 'span', 'ph': 'B',
         'ts': t0, 'tid': 1},
        {'name': 'step.dispatch', 'cat': 'span', 'ph': 'E',
         'ts': t0 + 500.0, 'tid': 1},
    ]
    if open_span:
        evs.append({'name': 'step.compiled', 'cat': 'span', 'ph': 'B',
                    'ts': t0 + 600.0, 'tid': 1})
        evs.append({'name': 'step.compiled', 'cat': 'span', 'ph': 'E',
                    'ts': t0 + 700.0, 'tid': 1,
                    'args': {'flushed': True}})
    return {'traceEvents': evs, 'rank': rank,
            'clock_offset_us': offset_us}


def test_stitch_traces_shifts_remaps_and_validates(tmp_path):
    p0 = tmp_path / 'r0.json'
    p1 = tmp_path / 'r1.json'
    out = tmp_path / 'fleet.json'
    json.dump(_rank_doc(0, 0.0), open(p0, 'w'))
    json.dump(_rank_doc(1, 2500.0, open_span=True), open(p1, 'w'))
    r = subprocess.run(
        [sys.executable, os.path.join(TOOLS, 'stitch_traces.py'),
         '-o', str(out), str(p0), str(p1)],
        capture_output=True, text=True)
    assert r.returncode == 0, (r.stdout, r.stderr)
    # the wedged rank's open span is called out on the shared timeline
    assert 'OPEN at dump time' in r.stdout and 'rank 1' in r.stdout
    doc = json.load(open(out))
    assert doc['stitch']['ranks'] == [0, 1]
    by_pid = {}
    for e in doc['traceEvents']:
        if e.get('ph') == 'B' and e['name'] == 'step.dispatch':
            by_pid[e['pid']] = e['ts']
    # rank 1's events were shifted into the coordinator timebase
    assert by_pid[1] - by_pid[0] == 2500.0
    r2 = subprocess.run(
        [sys.executable, os.path.join(TOOLS, 'check_trace.py'),
         str(out)], capture_output=True, text=True)
    assert r2.returncode == 0, (r2.stdout, r2.stderr)


def test_stitch_rejects_duplicate_ranks(tmp_path):
    p0 = tmp_path / 'a.json'
    p1 = tmp_path / 'b.json'
    json.dump(_rank_doc(0, 0.0), open(p0, 'w'))
    json.dump(_rank_doc(0, 0.0), open(p1, 'w'))
    r = subprocess.run(
        [sys.executable, os.path.join(TOOLS, 'stitch_traces.py'),
         '-o', str(tmp_path / 'o.json'), str(p0), str(p1)],
        capture_output=True, text=True)
    assert r.returncode == 2
    assert 'duplicate ranks' in r.stderr


def test_dump_rank_trace_embeds_rank_and_offset(tmp_path):
    trace.enable()
    with trace.span('step.dispatch'):
        pass
    path = str(tmp_path / 'rank.json')
    fleet.dump_rank_trace(path, membership=None)
    doc = json.load(open(path))
    assert doc['rank'] == 0 and doc['clock_offset_us'] == 0.0
    assert any(e.get('name') == 'step.dispatch'
               for e in doc['traceEvents'])


# ---------------------------------------------------------------------------
# the two-process drill (acceptance): endpoints on both ranks, fleet
# view with skew, injected straggler flagged + named, comm agreement,
# stitched trace clean
# ---------------------------------------------------------------------------

@pytest.mark.slow  # duplicated by the dryrun_multichip fleet stage
def test_fleet_drill_end_to_end(tmp_path):
    from mxnet_tpu.resilience.drill import run_fleet_drill
    result = run_fleet_drill(str(tmp_path))
    assert result['ok']
    assert result['straggler']['rank'] == result['slow_rank'] == 1
    assert 'STRAGGLER SUSPECTED: rank 1' in result['watchdog_verdict']
    assert result['comm_agreement'] and \
        all(v > 0 for v in result['comm_agreement'].values())
    assert result['skew_ms'] > 0
    assert 0 < min(result['snapshot_bytes'].values()) <= \
        max(result['snapshot_bytes'].values()) < 2048
    assert os.path.exists(result['stitched'])
