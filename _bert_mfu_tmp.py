import time, numpy as onp, jax
import mxnet_tpu as mx
from mxnet_tpu import nd
from mxnet_tpu.models import BertForPretraining
from mxnet_tpu.models.bert import bert_base_config, bert_pretrain_loss
from mxnet_tpu.parallel import make_mesh, ShardedTrainStep

t_start = time.time()
def log(msg):
    print(f"[{time.time()-t_start:7.1f}s] {msg}", flush=True)

cfg = bert_base_config()
model = BertForPretraining(cfg)
model.initialize(mx.init.Normal(0.02))
log("model init done")
batch, seq = 32, 512
mesh = make_mesh((1,), ('dp',), devices=jax.devices()[:1])
class LossWrap:
    def __call__(self, mlm, nsp, labels, nsp_labels):
        return bert_pretrain_loss(mlm, nsp, labels, nsp_labels)
step = ShardedTrainStep(model, LossWrap(), 'adamw', {'learning_rate': 1e-4}, mesh=mesh)
rng = onp.random.RandomState(0)
tokens = nd.array(rng.randint(0, cfg['vocab_size'], (batch, seq)).astype(onp.int32))
types = nd.array(onp.zeros((batch, seq), onp.int32))
labels = nd.array(rng.randint(0, cfg['vocab_size'], (batch, seq)).astype(onp.int32))
nsp = nd.array(rng.randint(0, 2, (batch,)).astype(onp.int32))
for i in range(3):
    v = float(step((tokens, types), (labels, nsp)).asnumpy())
    log(f"warmup {i}: loss={v:.3f}")
N = 10
t0 = time.time()
for i in range(N):
    loss = step((tokens, types), (labels, nsp))
v = float(loss.asnumpy())
dt = (time.time() - t0) / N
sps = batch / dt
P = sum(int(onp.prod(p.shape)) for p in model.collect_params().values())
tokens_per_step = batch * seq
flops = 6 * P * tokens_per_step + 12 * cfg['layers'] * cfg['hidden'] * seq * tokens_per_step
mfu = flops / dt / 197e12
log(f"params={P/1e6:.1f}M step={dt*1000:.1f}ms samples/sec={sps:.2f}")
log(f"model FLOPs/step={flops/1e12:.2f}T -> MFU={mfu*100:.1f}% (197 TFLOPs bf16 peak)")
