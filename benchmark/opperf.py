"""Per-operator benchmark suite (ref: benchmark/opperf/ — the reference
publishes per-op fwd/bwd latency tables, benchmark/opperf/results/
mxnet_operator_benchmark_results_{cpu,gpu}.md; BASELINE.md row
"Per-operator fwd/bwd latency").

Registry-driven: times forward (and backward where the op is
differentiable) for a representative profile of each operator group at
reference-comparable shapes, compiled with jit (the deployment path), and
emits a markdown table plus a JSON lines file.

Usage:
    python benchmark/opperf.py                 # all profiled ops
    python benchmark/opperf.py --ops dot relu  # a subset
    python benchmark/opperf.py --json out.jsonl --md out.md
"""
from __future__ import annotations

import argparse
import json
import os
import sys
import time

sys.path.insert(0, os.path.join(os.path.dirname(__file__), os.pardir))

import numpy as onp


def _r(*shape):
    return onp.random.RandomState(0).randn(*shape).astype(onp.float32)


def default_profiles():
    """op name -> zero-arg factory returning (inputs, kwargs). Factories
    keep startup lazy: only the selected ops' arrays are materialized.
    Shapes follow the reference opperf defaults (1024-ish)."""
    B, M, N, K = 32, 1024, 1024, 1024
    img = lambda: _r(32, 3, 224, 224)
    return {
        # tensor/elemwise
        'add_n': lambda: ([_r(M, N), _r(M, N)], {}),
        'relu': lambda: ([_r(M, N)], {}),
        'sigmoid': lambda: ([_r(M, N)], {}),
        'tanh': lambda: ([_r(M, N)], {}),
        'exp': lambda: ([_r(M, N)], {}),
        'log': lambda: ([onp.abs(_r(M, N)) + 1.0], {}),
        'sqrt': lambda: ([onp.abs(_r(M, N))], {}),
        'square': lambda: ([_r(M, N)], {}),
        'broadcast_add': lambda: ([_r(M, N), _r(1, N)], {}),
        'broadcast_mul': lambda: ([_r(M, N), _r(1, N)], {}),
        'sum': lambda: ([_r(M, N)], {}),
        'mean': lambda: ([_r(M, N)], {}),
        'max': lambda: ([_r(M, N)], {}),
        'argmax': lambda: ([_r(M, N)], {'axis': 1}),
        'dot': lambda: ([_r(M, K), _r(K, N)], {}),
        'batch_dot': lambda: ([_r(B, 128, 128), _r(B, 128, 128)], {}),
        'transpose': lambda: ([_r(M, N)], {}),
        'reshape': lambda: ([_r(M, N)], {'shape': (N, M)}),
        'slice': lambda: ([_r(M, N)], {'begin': (0, 0), 'end': (M // 2, N // 2)}),
        'take': lambda: ([_r(M, N),
                  onp.random.RandomState(0).randint(0, M, (256,))
                  .astype(onp.int32)], {}),
        'one_hot': lambda: ([onp.random.RandomState(0).randint(0, 64, (M,))
                     .astype(onp.int32)], {'depth': 64}),
        'topk': lambda: ([_r(M, N)], {'k': 8}),
        'sort': lambda: ([_r(M, N)], {}),
        'clip': lambda: ([_r(M, N)], {'a_min': -0.5, 'a_max': 0.5}),
        'abs': lambda: ([_r(M, N)], {}),
        'where': lambda: ([(_r(M, N) > 0), _r(M, N), _r(M, N)], {}),
        # NN core
        'fully_connected': lambda: ([_r(B, 1024), _r(512, 1024), _r(512)],
                           {'num_hidden': 512}),
        'convolution': lambda: ([img(), _r(64, 3, 3, 3), _r(64)],
                        {'kernel': (3, 3), 'num_filter': 64,
                         'pad': (1, 1)}),
        'pooling': lambda: ([img()], {'kernel': (2, 2), 'stride': (2, 2),
                            'pool_type': 'max'}),
        'activation': lambda: ([_r(M, N)], {'act_type': 'relu'}),
        'softmax': lambda: ([_r(B, 1000)], {}),
        'log_softmax': lambda: ([_r(B, 1000)], {}),
        'layer_norm': lambda: ([_r(B, 512, 768), _r(768), _r(768)], {}),
        'batch_norm': lambda: ([_r(B, 64, 56, 56), _r(64), _r(64), _r(64),
                       onp.abs(_r(64)) + 1.0], {}),
        'dropout': lambda: ([_r(M, N)], {'p': 0.5}),
        'embedding': lambda: ([onp.random.RandomState(0).randint(0, 1000, (B, 128))
                       .astype(onp.int32), _r(1000, 256)],
                      {'input_dim': 1000, 'output_dim': 256}),
        # attention
        'multi_head_attention': lambda: ([_r(B, 128, 512), _r(B, 128, 512),
                                  _r(B, 128, 512)], {'num_heads': 8}),
        # optimizer update ops
        'sgd_update': lambda: ([_r(M, N), _r(M, N)], {'lr': 0.1}),
        'adam_update': lambda: ([_r(M, N), _r(M, N), _r(M, N),
                         onp.abs(_r(M, N))], {'lr': 0.1}),
    }


def bench_op(opname, inputs, kwargs, iters=20, warmup=3):
    import jax
    import jax.numpy as jnp
    from mxnet_tpu.base import get_op

    opdef = get_op(opname)
    datas = [jnp.asarray(x) for x in inputs]
    fwd = jax.jit(lambda *a: opdef.fn(*a, **kwargs))

    def _time(fn, args):
        out = fn(*args)
        jax.block_until_ready(out)
        t0 = time.perf_counter()
        for _ in range(iters):
            out = fn(*args)
        jax.block_until_ready(out)
        return (time.perf_counter() - t0) / iters * 1e3

    for _ in range(warmup):
        jax.block_until_ready(fwd(*datas))
    fwd_ms = _time(fwd, datas)

    bwd_ms = None
    if not opdef.nograd:
        try:
            argnums = tuple(i for i, d in enumerate(datas)
                            if hasattr(d, 'dtype') and
                            jnp.issubdtype(d.dtype, jnp.floating))
            if argnums:
                def loss(*a):
                    out = opdef.fn(*a, **kwargs)
                    outs = out if isinstance(out, (list, tuple)) else [out]
                    return sum(jnp.sum(o.astype(jnp.float32))
                               for o in outs
                               if jnp.issubdtype(o.dtype, jnp.floating))
                g = jax.jit(jax.grad(loss, argnums=argnums))
                jax.block_until_ready(g(*datas))
                bwd_ms = _time(g, datas)
        except Exception:
            bwd_ms = None
    return fwd_ms, bwd_ms


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument('--ops', nargs='*', default=None)
    ap.add_argument('--iters', type=int, default=20)
    ap.add_argument('--json', default=None)
    ap.add_argument('--md', default=None)
    args = ap.parse_args()

    import jax
    dev = jax.devices()[0]
    profiles = default_profiles()
    names = args.ops or sorted(profiles)
    rows = []
    for name in names:
        if name not in profiles:
            print(f"[opperf] no profile for {name}, skipping",
                  file=sys.stderr)
            continue
        inputs, kwargs = profiles[name]()
        try:
            fwd_ms, bwd_ms = bench_op(name, inputs, kwargs,
                                      iters=args.iters)
            rows.append({'op': name, 'fwd_ms': round(fwd_ms, 4),
                         'bwd_ms': (round(bwd_ms, 4)
                                    if bwd_ms is not None else None)})
            print(f"[opperf] {name}: fwd {fwd_ms:.4f}ms"
                  + (f" bwd {bwd_ms:.4f}ms" if bwd_ms else ""),
                  file=sys.stderr)
        except Exception as e:
            rows.append({'op': name, 'error': repr(e)[:200]})
            print(f"[opperf] {name}: FAILED {e!r}", file=sys.stderr)

    md = ['| Operator | Fwd (ms) | Bwd (ms) |', '|---|---|---|']
    for r in rows:
        if 'error' in r:
            md.append(f"| {r['op']} | error | |")
        else:
            b = '' if r['bwd_ms'] is None else f"{r['bwd_ms']}"
            md.append(f"| {r['op']} | {r['fwd_ms']} | {b} |")
    table = '\n'.join(md)
    header = (f"# Operator benchmark — device {dev.platform} "
              f"({getattr(dev, 'device_kind', '?')})\n\n")
    if args.md:
        with open(args.md, 'w') as f:
            f.write(header + table + '\n')
    if args.json:
        with open(args.json, 'w') as f:
            for r in rows:
                f.write(json.dumps(r) + '\n')
    print(header + table)


if __name__ == '__main__':
    main()
